"""Service-level objectives as multi-window burn rates.

A threshold alert ("p99 > 250 ms") pages on blips and sleeps through
slow burns; an SLO pages on **budget consumption velocity**. An
:class:`SLO` declares an objective over a window — "99% of predicts
under 50 ms over 30 minutes" — and :class:`SLOMonitor` evaluates it
the way the SRE workbook prescribes: the **burn rate** is the ratio
of the observed bad fraction to the budget (``1 - objective``), and
a breach requires BOTH a long window (enough evidence) and a short
window (still happening right now) to exceed the factor — a spike
that already recovered cannot page, and neither can a stale incident.

Good/total counts come straight off the metrics registry:

- **latency SLOs** (``threshold_s`` set): good = requests at or under
  the threshold, read from the cumulative buckets of a registered
  histogram (``serving_latency_seconds`` by default);
- **availability SLOs** (no threshold): good = total - errors, read
  from the ``serving_requests_total`` / ``serving_errors_total``
  counter pair.

The monitor keeps a ring of ``(t, good, total)`` samples per SLO (the
registry's instruments are cumulative, so windowed rates are sample
deltas), and publishes its verdicts back onto the registry:
``slo_burn_rate{slo,window}`` gauges plus a 0/1 ``slo_breach{slo}``
pull gauge whose read triggers a (rate-limited) evaluation — so an
``AlertManager`` rule over ``slo_breach`` (see :meth:`install`) stays
fresh whether it is polled by ``/healthz``, the background alert
thread, or a scraper. On a fresh breach the monitor captures the
**offending trace ids** (the exemplars sitting in the buckets above
the threshold) into the flight recorder and dumps a bundle: the page
arrives with the traces that burned the budget.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability.registry import (Counter,
                                                       Histogram,
                                                       MetricsRegistry)

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["SLO", "BurnWindow", "SLOMonitor", "compare_cohorts"]


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate condition: fire when burn exceeds
    ``factor`` over BOTH the long and the short window."""

    short_s: float
    long_s: float
    factor: float
    severity: str = "page"


def default_burn_windows(window_s: float) -> List[BurnWindow]:
    """The SRE-workbook pairs, scaled to the SLO window: a fast-burn
    page (budget gone in ~window/14 at this rate) and a slow-burn
    ticket."""
    w = float(window_s)
    return [BurnWindow(short_s=max(15.0, w / 30.0),
                       long_s=max(60.0, w / 6.0),
                       factor=14.4, severity="page"),
            BurnWindow(short_s=max(60.0, w / 6.0), long_s=w,
                       factor=6.0, severity="ticket")]


def compare_cohorts(baseline: dict, candidate: dict, *,
                    min_requests: int = 50,
                    max_p99_ratio: float = 1.5,
                    max_error_rate_delta: float = 0.02,
                    p99_floor_ms: float = 5.0) -> dict:
    """Comparative two-cohort SLO evaluation — the rollout gate.

    Each cohort is ``{"requests": int, "errors": int, "p99_ms":
    float}`` (a FleetCollector ``cohort_stats`` row). The verdict is
    evidence-based, never wall-clock-only:

    - ``hold``: the candidate has fewer than ``min_requests``
      requests — not enough evidence to promote OR to roll back;
    - ``fail``: candidate error rate exceeds the baseline's by more
      than ``max_error_rate_delta`` (gate ``error_rate``), or
      candidate p99 exceeds ``max_p99_ratio`` x the baseline p99
      (gate ``p99`` — the baseline is floored at ``p99_floor_ms``
      so a sub-millisecond baseline cannot flunk a healthy
      candidate on noise);
    - ``pass``: both checks clear with sufficient evidence.

    Returns ``{"verdict", "gate", "detail", "baseline",
    "candidate"}`` — ``gate`` names the failed (or held) check,
    None on pass."""
    base_n = int(baseline.get("requests", 0) or 0)
    cand_n = int(candidate.get("requests", 0) or 0)
    out = {"verdict": "pass", "gate": None, "detail": "",
           "baseline": dict(baseline), "candidate": dict(candidate)}
    if cand_n < int(min_requests):
        out.update(verdict="hold", gate="min_requests",
                   detail=f"candidate has {cand_n} request(s), "
                          f"gate needs {int(min_requests)} — "
                          f"holding, not promoting")
        return out
    base_rate = (float(baseline.get("errors", 0) or 0) / base_n
                 if base_n else 0.0)
    cand_rate = float(candidate.get("errors", 0) or 0) / cand_n
    if cand_rate > base_rate + float(max_error_rate_delta):
        out.update(verdict="fail", gate="error_rate",
                   detail=f"candidate error rate {cand_rate:.4f} "
                          f"exceeds baseline {base_rate:.4f} + "
                          f"delta {float(max_error_rate_delta)}")
        return out
    base_p99 = max(float(baseline.get("p99_ms", 0.0) or 0.0),
                   float(p99_floor_ms))
    cand_p99 = float(candidate.get("p99_ms", 0.0) or 0.0)
    if cand_p99 > float(max_p99_ratio) * base_p99:
        out.update(verdict="fail", gate="p99",
                   detail=f"candidate p99 {cand_p99:.1f}ms exceeds "
                          f"{float(max_p99_ratio)}x baseline "
                          f"{base_p99:.1f}ms")
        return out
    out["detail"] = (f"candidate ok over {cand_n} request(s): "
                     f"error rate {cand_rate:.4f} vs baseline "
                     f"{base_rate:.4f}, p99 {cand_p99:.1f}ms vs "
                     f"baseline {base_p99:.1f}ms")
    return out


@dataclasses.dataclass
class SLO:
    """One declarative objective.

    ``threshold_s`` set → latency SLO over a histogram; unset →
    availability SLO over the good/total counter pair."""

    name: str
    objective: float = 0.99
    threshold_s: Optional[float] = None
    metric: str = "serving_latency_seconds"
    labels: Optional[Dict[str, str]] = None
    window_s: float = 1800.0
    total_metric: str = "serving_requests_total"
    bad_metric: str = "serving_errors_total"
    windows: Optional[List[BurnWindow]] = None
    description: str = ""

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}")
        if self.window_s <= 0:
            raise ValueError("window_s must be positive")
        if self.windows is None:
            self.windows = default_burn_windows(self.window_s)

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @classmethod
    def from_config(cls, cfg: dict) -> "SLO":
        """Build from the JSON rule schema (see README "Request
        tracing & SLOs"): ``threshold_ms``/``window_m`` are the
        human-units spellings; ``endpoint`` is shorthand for
        ``labels={"endpoint": ...}``."""
        cfg = dict(cfg)
        if "threshold_ms" in cfg:
            cfg["threshold_s"] = float(cfg.pop("threshold_ms")) / 1e3
        if "window_m" in cfg:
            cfg["window_s"] = float(cfg.pop("window_m")) * 60.0
        if "endpoint" in cfg:
            labels = dict(cfg.get("labels") or {})
            labels["endpoint"] = cfg.pop("endpoint")
            cfg["labels"] = labels
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown SLO config key(s) {sorted(unknown)}; "
                f"known: {sorted(known)}")
        return cls(**cfg)


class _SloState:
    __slots__ = ("samples", "breached", "burns", "last_change",
                 "gauges")

    def __init__(self):
        # (t, good, total) — cumulative readings; windowed rates are
        # deltas between samples
        self.samples: collections.deque = collections.deque(
            maxlen=4096)
        self.breached = False
        self.burns: Dict[str, float] = {}
        self.last_change: Optional[float] = None
        # burn-rate gauges, pre-created at add() time (window names
        # are known up front; instruments are never created inside
        # the evaluation loop — the GL006 metrics-hygiene contract)
        self.gauges: Dict[str, object] = {}


class SLOMonitor:
    """Evaluate SLO burn rates against one registry.

    ``evaluate()`` is cheap (a handful of counter reads) and
    rate-limited, so /healthz handlers, gauge pulls and the alert
    thread can all trigger it without stacking samples. ``clock`` is
    injectable for deterministic tests."""

    def __init__(self, registry: MetricsRegistry,
                 slos: Sequence[SLO] = (),
                 clock: Callable[[], float] = time.monotonic,
                 min_eval_interval_s: float = 1.0,
                 on_breach: Optional[Callable[[dict], None]] = None):
        self.registry = registry
        self.clock = clock
        self.min_eval_interval_s = min_eval_interval_s
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._slos: Dict[str, SLO] = {}
        self._state: Dict[str, _SloState] = {}
        self._last_eval = -float("inf")
        for s in slos:
            self.add(s)

    @classmethod
    def from_config(cls, registry: MetricsRegistry, config,
                    **kw) -> "SLOMonitor":
        """``config`` is a list of rule dicts, a JSON string, a path
        to a JSON file holding either, or ``@path`` (CLI idiom)."""
        if isinstance(config, str):
            if config.startswith("@"):
                with open(config[1:], encoding="utf-8") as f:
                    data = json.load(f)
            else:
                try:
                    data = json.loads(config)
                except ValueError:
                    with open(config, encoding="utf-8") as f:
                        data = json.load(f)
        else:
            data = config
        if isinstance(data, dict):
            data = data.get("slos", [data])
        return cls(registry, [SLO.from_config(c) for c in data], **kw)

    def add(self, slo: SLO) -> SLO:
        st = _SloState()
        with self._lock:
            self._slos[slo.name] = slo
            self._state[slo.name] = st
        # verdict gauges: breach is a PULL gauge so any reader (the
        # alert thread, a scraper) gets a fresh, rate-limited
        # evaluation; burn rates are SET gauges pre-created here and
        # updated by evaluate()
        self.registry.gauge(
            "slo_breach",
            help="1 while the SLO's multi-window burn-rate condition "
                 "holds", labels={"slo": slo.name},
            fn=lambda name=slo.name: self._breach_value(name))
        for w in slo.windows:
            for wname in (f"{int(w.long_s)}s", f"{int(w.short_s)}s"):
                st.gauges[wname] = self.registry.gauge(
                    "slo_burn_rate",
                    help="error-budget burn rate (bad fraction / "
                         "budget) over the trailing window",
                    labels={"slo": slo.name, "window": wname})
        return slo

    def remove(self, name: str) -> None:
        """Drop one SLO and unregister its verdict gauges — the
        pairing half of ``add`` (GL009): a monitor whose SLO set is
        reconfigured (or a discarded monitor, via :meth:`close`)
        must not leave breach/burn gauges whose callbacks pin it on
        the shared registry."""
        with self._lock:
            slo = self._slos.pop(name, None)
            self._state.pop(name, None)
        if slo is None:
            return
        self.registry.unregister("slo_breach",
                                 labels={"slo": slo.name})
        for w in slo.windows:
            for wname in (f"{int(w.long_s)}s", f"{int(w.short_s)}s"):
                self.registry.unregister(
                    "slo_burn_rate",
                    labels={"slo": slo.name, "window": wname})

    def close(self) -> None:
        """Unregister every SLO's gauges (see :meth:`remove`)."""
        with self._lock:
            names = list(self._slos)
        for name in names:
            self.remove(name)

    # ------------------------------------------------------------------
    # readings
    # ------------------------------------------------------------------
    def _read(self, slo: SLO) -> Optional[Tuple[float, float]]:
        """(good, total) cumulative counts, or None when the metric
        is not registered yet (no traffic — nothing to burn)."""
        if slo.threshold_s is not None:
            m = self.registry.get(slo.metric, slo.labels)
            if not isinstance(m, Histogram):
                return None
            edges, counts, count, _ = m.bucket_counts()
            good = 0
            for edge, c in zip(edges, counts):
                # bucket i holds observations <= edges[i]; a bucket
                # straddling the threshold counts as bad
                # (conservative)
                if edge <= slo.threshold_s * (1 + 1e-9):
                    good += c
            return float(good), float(count)
        total = self.registry.get(slo.total_metric, slo.labels)
        bad = self.registry.get(slo.bad_metric, slo.labels)
        if not isinstance(total, Counter):
            return None
        t = float(total.value)
        b = float(bad.value) if isinstance(bad, Counter) else 0.0
        return t - b, t

    @staticmethod
    def _window_delta(samples, now: float, window_s: float,
                      current: Tuple[float, float]
                      ) -> Tuple[float, float]:
        """good/total delta between now and the newest sample at
        least ``window_s`` old (falling back to the oldest sample —
        early in a run the window is simply shorter)."""
        base = None
        for t, g, tot in samples:          # oldest → newest
            if t <= now - window_s:
                base = (g, tot)
            else:
                break
        if base is None and samples:
            _, g, tot = samples[0]
            base = (g, tot)
        if base is None:
            return 0.0, 0.0
        return current[0] - base[0], current[1] - base[1]

    def _burn(self, slo: SLO, samples, now: float,
              window_s: float, current) -> float:
        d_good, d_total = self._window_delta(samples, now, window_s,
                                             current)
        if d_total <= 0:
            return 0.0
        bad_frac = (d_total - d_good) / d_total
        return bad_frac / slo.budget

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _breach_value(self, name: str) -> float:
        self.evaluate()
        with self._lock:
            st = self._state.get(name)
            return 1.0 if st is not None and st.breached else 0.0

    def evaluate(self, force: bool = False) -> List[dict]:
        """One (rate-limited) evaluation pass; returns breach /
        recovery transitions as dicts."""
        now = self.clock()
        with self._lock:
            if not force and now - self._last_eval \
                    < self.min_eval_interval_s:
                return []
            self._last_eval = now
            slos = list(self._slos.values())
        changes = []
        for slo in slos:
            ch = self._evaluate_one(slo, now)
            if ch is not None:
                changes.append(ch)
        return changes

    def _evaluate_one(self, slo: SLO, now: float) -> Optional[dict]:
        current = self._read(slo)
        st = self._state.get(slo.name)
        if current is None or st is None:
            return None
        burns: Dict[str, float] = {}
        breached_by = None
        for w in slo.windows:
            b_long = self._burn(slo, st.samples, now, w.long_s,
                                current)
            b_short = self._burn(slo, st.samples, now, w.short_s,
                                 current)
            burns[f"{int(w.long_s)}s"] = round(b_long, 3)
            burns[f"{int(w.short_s)}s"] = round(b_short, 3)
            if b_long > w.factor and b_short > w.factor \
                    and breached_by is None:
                breached_by = {"severity": w.severity,
                               "factor": w.factor,
                               "long_s": w.long_s,
                               "short_s": w.short_s,
                               "burn_long": round(b_long, 3),
                               "burn_short": round(b_short, 3)}
        st.samples.append((now, current[0], current[1]))
        for wname, b in burns.items():
            g = st.gauges.get(wname)
            if g is not None:
                g.set(b)
        with self._lock:
            st.burns = burns
            was = st.breached
            st.breached = breached_by is not None
            if st.breached != was:
                st.last_change = now
        if breached_by is not None and not was:
            change = {"event": "breach", "slo": slo.name,
                      "objective": slo.objective,
                      "threshold_s": slo.threshold_s,
                      "window_s": slo.window_s, **breached_by}
            self._on_breach(slo, change)
            return change
        if breached_by is None and was:
            logger.warning("SLO recovered: %s", slo.name)
            return {"event": "recover", "slo": slo.name}
        return None

    def _on_breach(self, slo: SLO, change: dict) -> None:
        traces = self.offending_traces(slo)
        change["traces"] = traces
        logger.warning(
            "SLO BREACH: %s — burning %.1fx budget over %ss "
            "(%.1fx over %ss); offending traces: %s",
            slo.name, change["burn_long"], int(change["long_s"]),
            change["burn_short"], int(change["short_s"]),
            ", ".join(traces) or "<none sampled>")
        # ship the offending trace ids with the page: the flight
        # recorder bundle is the artifact the on-call opens first
        try:
            from deeplearning4j_tpu.observability import (
                flight_recorder)
            rec = flight_recorder.get_recorder()
            if rec is not None:
                rec.record("slo_breach", **change)
                rec.dump(reason=f"slo_breach_{slo.name}", force=False)
        except Exception:
            logger.exception("flight-recorder SLO capture failed")
        if self.on_breach is not None:
            try:
                self.on_breach(change)
            except Exception:
                logger.exception("on_breach callback failed")

    def offending_traces(self, slo: SLO, limit: int = 10
                         ) -> List[str]:
        """Trace ids sitting as exemplars in the buckets past the
        latency threshold (for availability SLOs: every exemplar of
        the latency histogram sharing the SLO's labels) — concrete
        requests that burned the budget."""
        m = self.registry.get(
            slo.metric if slo.threshold_s is not None
            else "serving_latency_seconds", slo.labels)
        if not isinstance(m, Histogram):
            return []
        out = []
        for ex in m.exemplars():
            if slo.threshold_s is not None \
                    and ex["value"] <= slo.threshold_s:
                continue
            tid = ex["labels"].get("trace_id")
            if tid and tid not in out:
                out.append(tid)
        return out[-limit:]

    def any_breached(self, evaluate: bool = True) -> bool:
        """True while ANY registered SLO's multi-window burn-rate
        condition holds — the autoscaler's scale-up trigger (one
        rate-limited evaluation per call by default, so a fast
        control loop cannot stack samples)."""
        if evaluate:
            self.evaluate()
        with self._lock:
            return any(st.breached for st in self._state.values())

    # ------------------------------------------------------------------
    def status(self) -> List[dict]:
        """Per-SLO verdict for /healthz and the UI."""
        with self._lock:
            slos = dict(self._slos)
            states = {n: (st.breached, dict(st.burns))
                      for n, st in self._state.items()}
        out = []
        for name, slo in slos.items():
            breached, burns = states.get(name, (False, {}))
            out.append({"name": name, "objective": slo.objective,
                        "threshold_ms":
                            None if slo.threshold_s is None
                            else slo.threshold_s * 1e3,
                        "window_s": slo.window_s,
                        "burn_rates": burns, "breached": breached,
                        "description": slo.description})
        return out

    def install(self, manager) -> None:
        """Register one ``AlertRule`` per SLO on the ``slo_breach``
        gauge: the AlertManager's for-duration/debounce/callback
        machinery (and /healthz's degraded state) now covers SLO
        breaches with zero new wiring."""
        from deeplearning4j_tpu.observability.alerts import AlertRule
        with self._lock:
            slos = list(self._slos.values())
        for slo in slos:
            manager.add_rule(AlertRule(
                name=f"slo_burn:{slo.name}",
                metric="slo_breach", labels={"slo": slo.name},
                op=">=", threshold=1.0,
                severity="critical",
                description=slo.description
                or f"SLO {slo.name} burn-rate breach "
                   f"(objective {slo.objective:g}, window "
                   f"{slo.window_s:g}s)"))
