"""Fleet observability plane: one collector over N processes.

Every observability primitive in this repo — the metrics registry,
the tracer ring, SLO burn rates, the flight recorder — is process
local. A serving fleet is not: a trace id spans router -> prefill ->
decode yet its spans are stranded in three separate ring buffers, and
"fleet p99" exists nowhere until someone hand-merges N ``/metrics``
payloads. This module is that someone.

:class:`FleetCollector` runs a pull loop over every fleet member (the
router plus each replica) and provides four things:

- **Merged metrics.** Each member's OpenMetrics exposition is parsed
  and folded into one fleet-level :class:`MetricsRegistry`: every
  series is re-published twice, once under its original key with a
  ``replica`` label (per-member view) and once under the original
  key unchanged (the fleet aggregate — counters/gauges summed,
  histograms merged **bucket-wise**, which is exact because every
  process builds its buckets from the same
  ``default_latency_buckets`` edges). The merged registry re-exposes
  Prometheus/OpenMetrics text and a JSON snapshot, and a bounded
  downsampled ring keeps a headline time series in memory.
- **Fleet SLOs.** The existing :class:`SLOMonitor` burn-rate
  machinery is pointed at the merged registry unchanged — its exact
  ``(name, labels)`` reads hit the aggregate series, so availability
  and latency objectives are judged at the FLEET level. Breaches feed
  an :class:`AlertManager` and, via :meth:`fleet_health`, the
  router's ``/healthz``.
- **Distributed traces.** Each member's ``/debug/trace-export`` is
  drained incrementally (a per-target ``seq`` cursor); spans are
  stitched by trace id into cross-process trees, each span stamped
  with its source ``replica`` and an absolute wall-clock timestamp
  (``origin_unix * 1e6 + ts_us``) so one request renders as one
  timeline: router root span, replica subtrees under it.
- **Incident bundles.** On a fleet-SLO breach or a member death the
  collector pulls a flight-recorder style bundle from every live
  member into ``incident-<stamp>-<reason>/<member>/`` with one
  cross-process MANIFEST.

The collector is an OBSERVER: it holds no lock any serving thread
takes, and every interaction with the fleet is a plain HTTP GET with
a short timeout. Killing the collector mid-soak must cause zero
serving failures — nothing in the data plane ever waits on it.

Fleet-level metric names exported by the collector itself:
``fleet_scrapes_total``, ``fleet_scrape_errors_total``,
``fleet_targets_up``, ``fleet_incidents_total``,
``fleet_trace_spans_total``, ``fleet_scrape_duration_seconds``.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import re
import socket
import sys
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, \
    Tuple

from deeplearning4j_tpu.observability.registry import (
    MetricsRegistry, Histogram)
from deeplearning4j_tpu.observability.slo import SLO, SLOMonitor
from deeplearning4j_tpu.observability.alerts import AlertManager

logger = logging.getLogger(__name__)

__all__ = ["FleetCollector", "parse_exposition", "merge_histograms",
           "render_status", "local_bundle_payload"]


# --------------------------------------------------------------------
# exposition parsing
# --------------------------------------------------------------------

def _unescape(s: str) -> str:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c == "\\" and i + 1 < n:
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ('"', "\\"):
                out.append(nxt)
            else:
                out.append(c)
                out.append(nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(tok: str) -> float:
    t = tok.strip()
    if t in ("+Inf", "Inf"):
        return math.inf
    if t == "-Inf":
        return -math.inf
    if t == "NaN":
        return math.nan
    return float(t)


# fast path for the overwhelmingly common series shape: every label
# value quoted, no escapes. The slow char-scan below only runs when
# a value contains a backslash escape (the greedy `\{.*\}` still
# pairs the braces correctly when a VALUE contains '{'/'}' — the
# tail after the last '}' is always numeric tokens)
_SERIES_FAST_RE = re.compile(
    r"([a-zA-Z_:][a-zA-Z0-9_:.]*)\{(.*)\}\s*(.*)")
_LABEL_FAST_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"\\]*)"(?:,|\Z)')

# one-regex-per-line sample parser — the scrape loop shares the
# serving process's GIL, so parse cost is directly serving cost.
# Groups: name, label blob, value, timestamp, exemplar blob,
# exemplar value, exemplar ts. Non-greedy label blobs mis-split on
# values containing '}' — the quote-count check below catches that
# (and escapes) and falls back to the char-scan.
_SAMPLE_RE = re.compile(
    r'([a-zA-Z_:][a-zA-Z0-9_:.]*)'
    r'(?:\{(.*?)\})?'
    r'[ \t]+([^ \t#]+)'
    r'(?:[ \t]+([^ \t#]+))?'
    r'(?:[ \t]*#[ \t]+\{(.*?)\}[ \t]+([^ \t]+)(?:[ \t]+([^ \t]+))?)?'
    r'[ \t\r]*$')

# label blobs repeat verbatim across series lines and scrape cycles
# (every bucket of a histogram, every cycle of a stable fleet) —
# memoize blob -> labels dict. Bounded: pathological cardinality
# (ids in label values) clears rather than grows without limit.
_LABELS_CACHE: Dict[str, Dict[str, str]] = {}


def _parse_label_blob(blob: str) -> Optional[Dict[str, str]]:
    """Labels for a regex-split blob, or None when the blob smells
    mis-split (escapes, or a '}' inside a quoted value truncated the
    non-greedy match) — the caller then re-parses the WHOLE line with
    the char-scan, which cannot mis-pair braces."""
    cached = _LABELS_CACHE.get(blob)
    if cached is None:
        pairs = _LABEL_FAST_RE.findall(blob)
        if blob.count('"') != 2 * len(pairs):
            return None
        cached = dict(pairs)
        if len(_LABELS_CACHE) > 20_000:
            _LABELS_CACHE.clear()
        _LABELS_CACHE[blob] = cached
    return dict(cached)


def _split_series(line: str) -> Tuple[str, Dict[str, str], str]:
    """``name{labels} rest`` -> (name, labels dict, rest). The label
    block is scanned character-wise so quoted values may contain
    commas, spaces, or escaped quotes."""
    brace = line.find("{")
    sp = line.find(" ")
    if brace == -1 or (sp != -1 and sp < brace):
        name, _, rest = line.partition(" ")
        return name, {}, rest.strip()
    if "\\" not in line:
        m = _SERIES_FAST_RE.match(line)
        if m is not None:
            blob = m.group(2)
            pairs = _LABEL_FAST_RE.findall(blob)
            # only trust the fast parse when the pair regex consumed
            # the whole blob (leftovers mean an exotic shape)
            if _LABEL_FAST_RE.sub("", blob).strip(", \t") == "":
                return m.group(1), dict(pairs), m.group(3).strip()
    name = line[:brace]
    labels: Dict[str, str] = {}
    i = brace + 1
    n = len(line)
    key = []
    while i < n and line[i] != "}":
        if line[i] in (",", " "):
            i += 1
            continue
        key = []
        while i < n and line[i] not in ("=",):
            key.append(line[i])
            i += 1
        i += 1                                  # '='
        if i < n and line[i] == '"':
            i += 1
            val = []
            while i < n:
                c = line[i]
                if c == "\\" and i + 1 < n:
                    val.append(c)
                    val.append(line[i + 1])
                    i += 2
                    continue
                if c == '"':
                    i += 1
                    break
                val.append(c)
                i += 1
            labels["".join(key).strip()] = _unescape("".join(val))
    rest = line[i + 1:].strip()                 # past '}'
    return name, labels, rest


def _labels_key(labels: Dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def parse_exposition(text: str) -> Dict[str, Any]:
    """Parse a Prometheus classic / OpenMetrics text payload into

    ``{"counters": {(name, lk): value},
       "gauges":   {(name, lk): value},
       "histograms": {(name, lk): {edges, counts, count, sum,
                                   exemplars}},
       "help": {name: help_text}}``

    where ``lk`` is the sorted label tuple (``le`` stripped for
    histogram buckets) and ``counts`` is per-bucket (DE-cumulated,
    overflow last) — the shape :func:`merge_histograms` sums
    exactly. Exemplars (OpenMetrics ``# {...} v ts`` tails) are kept
    per bucket.
    """
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    counters: Dict[tuple, float] = {}
    gauges: Dict[tuple, float] = {}
    raw_h: Dict[tuple, dict] = {}

    for line in text.split("\n"):
        if not line:
            continue
        if line[0] in " \t":
            line = line.strip()
            if not line:
                continue
        if line[0] == "#":
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip() \
                    if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue                           # comments, # EOF
        exemplar = None
        name = None
        # fast path: the whole sample line in one regex pass — the
        # scrape loop shares a GIL with serving threads, and the
        # char-scan path costs several times more per line
        m = _SAMPLE_RE.match(line)
        if m is not None:
            blob = m.group(2)
            labels = _parse_label_blob(blob) if blob else {}
            if labels is not None:
                try:
                    value = _parse_value(m.group(3))
                    name = m.group(1)
                except ValueError:
                    continue
                exblob = m.group(5)
                if exblob is not None:
                    # exemplar label values rotate (trace ids) — skip
                    # the memo cache to keep it from churning
                    pairs = _LABEL_FAST_RE.findall(exblob)
                    el = dict(pairs) \
                        if exblob.count('"') == 2 * len(pairs) \
                        else _split_series("x{" + exblob + "} 0")[1]
                    try:
                        exemplar = (el, _parse_value(m.group(6)),
                                    float(m.group(7))
                                    if m.group(7) else 0.0)
                    except ValueError:
                        exemplar = None
        if name is None:
            # slow path: escapes or exotic shapes — OpenMetrics
            # exemplar rides after ' # '
            body = line
            if " # " in line:
                body, _, extail = line.partition(" # ")
                ename, elabels, erest = _split_series("x" + extail)
                etoks = erest.split()
                if etoks:
                    try:
                        exemplar = (elabels, _parse_value(etoks[0]),
                                    float(etoks[1]) if len(etoks) > 1
                                    else 0.0)
                    except ValueError:
                        exemplar = None
            name, labels, rest = _split_series(body)
            toks = rest.split()
            if not toks:
                continue
            try:
                value = _parse_value(toks[0])
            except ValueError:
                continue

        base = None
        part = None
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) \
                    and types.get(name[:-len(suf)]) == "histogram":
                base, part = name[:-len(suf)], suf
                break
        if part is not None:
            le = labels.pop("le", None)
            hk = (base, _labels_key(labels))
            h = raw_h.setdefault(hk, {"buckets": [], "sum": 0.0,
                                      "count": 0, "exemplars": {}})
            if part == "_bucket":
                h["buckets"].append((_parse_value(le)
                                     if le is not None else math.inf,
                                     value))
                if exemplar is not None and le is not None:
                    h["exemplars"][_parse_value(le)] = exemplar
            elif part == "_sum":
                h["sum"] = value
            else:
                h["count"] = int(value)
            continue

        kind = types.get(name)
        if kind is None and name.endswith("_total"):
            # OpenMetrics: the counter family header drops _total
            kind = types.get(name[:-len("_total")])
            if kind == "counter":
                helps.setdefault(name,
                                 helps.get(name[:-len("_total")], ""))
        if kind is None:
            kind = "counter" if name.endswith("_total") else "gauge"
        sk = (name, _labels_key(labels))
        if kind == "counter":
            counters[sk] = value
        else:
            gauges[sk] = value

    hists: Dict[tuple, dict] = {}
    for hk, h in raw_h.items():
        buckets = sorted(h["buckets"], key=lambda b: b[0])
        edges = [le for le, _ in buckets if not math.isinf(le)]
        counts: List[int] = []
        prev = 0.0
        for le, cum in buckets:
            if math.isinf(le):
                continue
            counts.append(int(cum - prev))
            prev = cum
        total = h["count"]
        counts.append(int(total - prev))            # overflow
        exemplars: Dict[int, tuple] = {}
        for le, ex in h["exemplars"].items():
            if math.isinf(le):
                exemplars[len(edges)] = ex
            else:
                for i, e in enumerate(edges):
                    if abs(e - le) <= 1e-9 * max(abs(e), abs(le), 1.0):
                        exemplars[i] = ex
                        break
        hists[hk] = {"edges": edges, "counts": counts,
                     "count": total, "sum": h["sum"],
                     "exemplars": exemplars}
    return {"counters": counters, "gauges": gauges,
            "histograms": hists, "help": helps}


def merge_histograms(parts: Sequence[dict]) -> dict:
    """Bucket-wise sum of parsed histograms — EXACT, not an
    approximation, because identical edges mean each merged bucket
    count is the plain integer sum of the members' bucket counts
    (merge is associative and order-independent; any quantile of the
    merged histogram brackets between the members' extremes).
    Raises ``ValueError`` on mismatched edges."""
    if not parts:
        raise ValueError("nothing to merge")
    edges = list(parts[0]["edges"])
    counts = [0] * (len(edges) + 1)
    count = 0
    total = 0.0
    exemplars: Dict[int, tuple] = {}
    for p in parts:
        if list(p["edges"]) != edges:
            raise ValueError(
                f"histogram edge mismatch: {len(p['edges'])} edges "
                f"vs {len(edges)}")
        for i, c in enumerate(p["counts"]):
            counts[i] += int(c)
        count += int(p["count"])
        total += float(p["sum"])
        for i, ex in p.get("exemplars", {}).items():
            # exactly one source survives per bucket: the freshest
            cur = exemplars.get(i)
            if cur is None or ex[2] >= cur[2]:
                exemplars[i] = ex
    return {"edges": edges, "counts": counts, "count": count,
            "sum": total, "exemplars": exemplars}


def _hist_quantile(edges: List[float], counts: List[int],
                   q: float) -> float:
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        if seen + c >= rank:
            lo = 0.0 if i == 0 else edges[i - 1]
            hi = edges[min(i, len(edges) - 1)]
            frac = (rank - seen) / c if c else 0.0
            return lo + (hi - lo) * min(1.0, frac)
        seen += c
    return edges[-1] if edges else 0.0


# --------------------------------------------------------------------
# bounded downsampled time-series ring
# --------------------------------------------------------------------

class _DownsampledRing:
    """Append-only series bounded at ``capacity`` points: when full,
    every second retained point is dropped and the keep-stride
    doubles, so the ring always spans the WHOLE history at halving
    resolution instead of forgetting the past like a plain deque."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(4, int(capacity))
        self._items: List[Any] = []
        self._stride = 1
        self._n = 0

    def append(self, item: Any) -> None:
        if self._n % self._stride == 0:
            self._items.append(item)
            if len(self._items) >= self.capacity:
                self._items = self._items[::2]
                self._stride *= 2
        self._n += 1

    def items(self) -> List[Any]:
        return list(self._items)

    @property
    def stride(self) -> int:
        return self._stride


# --------------------------------------------------------------------
# bundle payload (served by every member's /debug/bundle)
# --------------------------------------------------------------------

def local_bundle_payload(registry=None, tracer=None,
                         reason: str = "incident",
                         max_spans: int = 2000) -> dict:
    """The JSON form of a flight-recorder bundle, built in-process so
    a collector can pull it over HTTP instead of reading the member's
    filesystem: ``{"reason", "files": {name: content}}`` where
    ``events.jsonl`` content is a list of event dicts and everything
    else is a JSON object. Works with or without an installed
    :class:`FlightRecorder` — a member that never installed one still
    contributes metrics + traces + env."""
    files: Dict[str, Any] = {}
    files["env.json"] = {
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "python": sys.version.split()[0],
        "argv": list(sys.argv),
        "ts_unix": time.time(),
    }
    if registry is not None:
        try:
            files["metrics.json"] = registry.snapshot()
        except Exception:
            files["metrics.json"] = {"error": "snapshot failed"}
    if tracer is not None:
        try:
            evs = tracer.events()[-max_spans:]
            files["trace.json"] = {"events": evs,
                                   "dropped": tracer.dropped,
                                   "origin_unix":
                                       getattr(tracer, "_origin_unix",
                                               0.0)}
        except Exception:
            pass
    try:
        from deeplearning4j_tpu.observability import flight_recorder
        rec = flight_recorder.get_recorder()
        if rec is not None:
            files["events.jsonl"] = rec.events()
            files["recorder_env.json"] = rec.env_snapshot()
    except Exception:
        pass
    files["MANIFEST.json"] = {
        "reason": reason,
        "pid": os.getpid(),
        "ts_unix": time.time(),
        "files": sorted(k for k in files),
    }
    return {"reason": reason, "files": files}


# --------------------------------------------------------------------
# the collector
# --------------------------------------------------------------------

def _http_get(url: str, timeout: float) -> bytes:
    req = urllib.request.Request(url, method="GET")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        if resp.status != 200:
            raise OSError(f"GET {url} -> {resp.status}")
        return resp.read()


class FleetCollector:
    """Scrape loop + merged registry + trace store + incident writer.

    ``fleet``/``router`` targets are re-enumerated every cycle so
    replica churn (autoscaling, chaos kills, drains) is followed
    without re-configuration; ``targets`` adds static
    ``(name, base_url)`` members (a PS server, a remote fleet).

    The collector NEVER touches serving state: every member
    interaction is an HTTP GET with ``scrape_timeout_s``, failures
    only mark the target down. Instruments the collector did not
    create itself (its own SLO gauges, alert counters) are never
    overwritten by a scrape — the merge only mutates series it owns.
    """

    def __init__(self, fleet=None, router=None,
                 targets: Optional[Sequence[Tuple[str, str]]] = None,
                 interval_s: float = 1.0,
                 host: str = "127.0.0.1", port: int = 0,
                 slos: Sequence[SLO] = (),
                 incident_dir: Optional[str] = None,
                 incident_min_interval_s: float = 30.0,
                 scrape_timeout_s: float = 2.0,
                 ring_capacity: int = 512,
                 trace_capacity: int = 2048,
                 span_capacity: int = 100_000,
                 registry: Optional[MetricsRegistry] = None,
                 on_incident: Optional[Callable[[dict], None]] = None,
                 url_rewrite: Optional[Callable[[str, str],
                                                str]] = None):
        self.fleet = fleet
        self.router = router
        # (name, url) -> url hook: the collector's OWN network path
        # to each member. Network-chaos soaks route scrapes through
        # their own NetChaosProxy, independent of the router's hop
        # to the same replica — an asymmetric partition in one line.
        self.url_rewrite = url_rewrite
        self._static_targets = list(targets or [])
        self.interval_s = float(interval_s)
        self.host = host
        self.port = port
        self.incident_dir = incident_dir or os.getcwd()
        self.incident_min_interval_s = float(incident_min_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.trace_capacity = int(trace_capacity)
        self.span_capacity = int(span_capacity)
        self.on_incident = on_incident

        self.registry = registry or MetricsRegistry()
        self._lock = threading.Lock()
        # (name, label-tuple) -> instrument the collector created;
        # the merge only ever mutates instruments recorded here
        self._made: Dict[tuple, Any] = {}
        self._scraped_keys: set = set()
        self._ring = _DownsampledRing(ring_capacity)
        self._down: Dict[str, str] = {}       # target -> last error
        self._up: set = set()
        self._last_cycle_unix = 0.0
        self._cycles = 0

        # trace store: trace id -> list of spans (insertion-ordered
        # LRU; eviction drops whole traces oldest-first). _trace_seen
        # holds each trace's span ids so a re-export (cursor reset,
        # or members sharing one tracer in-process) never duplicates
        self._traces: "collections.OrderedDict[str, List[dict]]" = \
            collections.OrderedDict()
        self._trace_seen: Dict[str, set] = {}
        self._span_total = 0
        self._trace_cursors: Dict[str, int] = {}

        self._incidents: List[dict] = []
        self._last_incident_unix = -float("inf")
        self._breached_prev = False

        # fixed self-instruments, created ONCE (GL006)
        self._m_scrapes = self.registry.counter(
            "fleet_scrapes_total",
            help="collector scrape cycles completed")
        self._m_scrape_errors = self.registry.counter(
            "fleet_scrape_errors_total",
            help="failed member scrapes (any endpoint)")
        self._m_targets_up = self.registry.gauge(
            "fleet_targets_up",
            help="members whose last scrape succeeded")
        self._m_incidents = self.registry.counter(
            "fleet_incidents_total",
            help="incident bundles written")
        self._m_spans = self.registry.counter(
            "fleet_trace_spans_total",
            help="spans drained from member tracer rings")
        self._m_scrape_dur = self.registry.histogram(
            "fleet_scrape_duration_seconds",
            help="wall time of one full scrape cycle",
            buckets=[0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0])
        self._m_scrape_partitions = self.registry.counter(
            "fleet_scrape_partitions_total",
            help="members unreachable on the scrape path while the "
                 "fleet declared them up (asymmetric partition; "
                 "no incident written)")

        self.alerts = AlertManager(self.registry)
        self.slo_monitor: Optional[SLOMonitor] = None
        if slos:
            self.slo_monitor = SLOMonitor(
                self.registry, slos, on_breach=self._note_breach)
            self.slo_monitor.install(self.alerts)

        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        self._pending_breach: Optional[dict] = None

    # ---- targets ----
    def _targets(self) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = list(self._static_targets)
        if self.router is not None:
            out.append(("router",
                        f"http://{self.router.host}:"
                        f"{self.router.port}"))
        if self.fleet is not None:
            for r in self.fleet.snapshot():
                if getattr(r, "fleet_state", "up") == "dead":
                    continue
                out.append((f"replica-{r.id}",
                            f"http://{r.host}:{r.port}"))
        if self.url_rewrite is not None:
            out = [(name, self.url_rewrite(name, url))
                   for name, url in out]
        return out

    # ---- merge helpers (registry calls live here, outside any
    # loop body, and the created instrument is retained — the
    # GL006-sanctioned pattern) ----
    def _counter_abs(self, name: str, labels: Dict[str, str],
                     value: float, help_: str = "") -> Optional[tuple]:
        key = (name, _labels_key(labels))
        inst = self._made.get(key)
        if inst is None:
            if self.registry.get(name, labels) is not None:
                return None       # never clobber a local instrument
            inst = self.registry.counter(name, help=help_,
                                         labels=dict(labels) or None)
            self._made[key] = inst
        with inst._lock:
            inst._value = float(value)
        return key

    def _gauge_abs(self, name: str, labels: Dict[str, str],
                   value: float, help_: str = "") -> Optional[tuple]:
        key = (name, _labels_key(labels))
        inst = self._made.get(key)
        if inst is None:
            if self.registry.get(name, labels) is not None:
                return None
            inst = self.registry.gauge(name, help=help_,
                                       labels=dict(labels) or None)
            self._made[key] = inst
        inst.set(float(value))
        return key

    def _hist_abs(self, name: str, labels: Dict[str, str],
                  merged: dict, help_: str = "") -> Optional[tuple]:
        key = (name, _labels_key(labels))
        inst = self._made.get(key)
        if inst is not None and list(inst.edges) != \
                list(merged["edges"]):
            self.registry.unregister(name, dict(labels) or None)
            self._made.pop(key, None)
            inst = None
        if inst is None:
            if self.registry.get(name, labels) is not None:
                return None
            inst = self.registry.histogram(
                name, help=help_, labels=dict(labels) or None,
                buckets=merged["edges"])
            self._made[key] = inst
        with inst._lock:
            inst.counts = [int(c) for c in merged["counts"]]
            inst.count = int(merged["count"])
            inst.sum = float(merged["sum"])
            inst._exemplars = {
                int(i): (dict(ex[0]), float(ex[1]), float(ex[2]))
                for i, ex in merged.get("exemplars", {}).items()}
        return key

    # ---- one scrape cycle ----
    def scrape_once(self) -> dict:
        """One full pull: metrics merge, trace drain, SLO eval,
        incident check. Returns a cycle summary (targets up/down)."""
        t0 = time.perf_counter()
        targets = self._targets()
        parsed: Dict[str, dict] = {}
        errors: Dict[str, str] = {}
        for tname, url in targets:
            try:
                raw = _http_get(url + "/metrics?format=openmetrics",
                                self.scrape_timeout_s)
                parsed[tname] = parse_exposition(raw.decode())
            except Exception as e:
                errors[tname] = repr(e)
        self._merge(parsed)
        self._drain_traces(targets)
        died = self._note_liveness(targets, parsed, errors)
        if self.slo_monitor is not None:
            try:
                self.slo_monitor.evaluate(force=True)
            except Exception:
                logger.exception("fleet SLO evaluation failed")
        try:
            self.alerts.evaluate()
        except Exception:
            pass
        self._check_incidents(targets, died)
        self._append_ring_sample(targets, errors)
        self._m_scrapes.inc()
        if errors:
            self._m_scrape_errors.inc(len(errors))
        self._m_targets_up.set(len(parsed))
        self._m_scrape_dur.record(time.perf_counter() - t0)
        with self._lock:
            self._cycles += 1
            self._last_cycle_unix = time.time()
        return {"up": sorted(parsed), "down": errors}

    def _merge(self, parsed: Dict[str, dict]) -> None:
        new_keys: set = set()
        helps: Dict[str, str] = {}
        agg_c: Dict[tuple, float] = {}
        agg_g: Dict[tuple, float] = {}
        agg_h: Dict[tuple, List[dict]] = {}
        for tname, fam in parsed.items():
            helps.update(fam.get("help", {}))
            for (name, lk), v in fam["counters"].items():
                labels = dict(lk)
                agg_c[(name, lk)] = agg_c.get((name, lk), 0.0) + v
                labels["replica"] = tname
                k = self._counter_abs(name, labels, v,
                                      helps.get(name, ""))
                if k:
                    new_keys.add(k)
            for (name, lk), v in fam["gauges"].items():
                labels = dict(lk)
                agg_g[(name, lk)] = agg_g.get((name, lk), 0.0) + v
                labels["replica"] = tname
                k = self._gauge_abs(name, labels, v,
                                    helps.get(name, ""))
                if k:
                    new_keys.add(k)
            for (name, lk), h in fam["histograms"].items():
                labels = dict(lk)
                agg_h.setdefault((name, lk), []).append(h)
                labels["replica"] = tname
                k = self._hist_abs(name, labels, h,
                                   helps.get(name, ""))
                if k:
                    new_keys.add(k)
        for (name, lk), v in agg_c.items():
            k = self._counter_abs(name, dict(lk), v,
                                  helps.get(name, ""))
            if k:
                new_keys.add(k)
        for (name, lk), v in agg_g.items():
            k = self._gauge_abs(name, dict(lk), v,
                                helps.get(name, ""))
            if k:
                new_keys.add(k)
        for (name, lk), hs in agg_h.items():
            try:
                merged = merge_histograms(hs)
            except ValueError:
                logger.warning("fleet: skipping %s — edge mismatch "
                               "across members", name)
                continue
            k = self._hist_abs(name, dict(lk), merged,
                               helps.get(name, ""))
            if k:
                new_keys.add(k)
        with self._lock:
            stale = self._scraped_keys - new_keys
            self._scraped_keys = new_keys
        for (name, lk) in stale:
            self.registry.unregister(name, dict(lk) or None)
            self._made.pop((name, lk), None)

    # ---- traces ----
    # pages drained per member per cycle before giving up: a member
    # whose backlog outruns this is lagged, not wedged — the next
    # cycle resumes from the cursor
    _TRACE_PAGES_PER_CYCLE = 64

    def _drain_traces(self,
                      targets: List[Tuple[str, str]]) -> None:
        for tname, url in targets:
            for _ in range(self._TRACE_PAGES_PER_CYCLE):
                if not self._drain_trace_page(tname, url):
                    break

    def _drain_trace_page(self, tname: str, url: str) -> bool:
        """One ``trace-export`` page from one member; True when the
        member reported more backlog past the new cursor (drain the
        next page this same cycle). A scrape must catch the collector
        up to the member's head, not advance one page per cycle —
        paging once meant a backlog of N pages took N scrape
        intervals to surface a trace that was already complete."""
        since = self._trace_cursors.get(tname, 0)
        try:
            raw = _http_get(
                f"{url}/debug/trace-export?since={since}"
                f"&limit=5000", self.scrape_timeout_s)
            data = json.loads(raw.decode())
        except Exception:
            return False
        nxt = int(data.get("next", since))
        head = int(data.get("head", nxt))
        if head < since:
            # the member restarted (its seq space reset under
            # our cursor) — resync from zero on the next poll
            self._trace_cursors[tname] = 0
            return False
        self._trace_cursors[tname] = nxt
        origin = float(data.get("origin_unix", 0.0))
        spans = data.get("spans", [])
        if spans:
            self._merge_trace_page(tname, origin, spans)
        return bool(spans) and nxt < head

    def _merge_trace_page(self, tname: str, origin: float,
                          spans: List[dict]) -> None:
        with self._lock:
            for ev in spans:
                tid = ev.get("trace_id")
                if not tid:
                    continue
                bucket = self._traces.get(tid)
                if bucket is None:
                    bucket = self._traces[tid] = []
                    self._trace_seen[tid] = set()
                else:
                    self._traces.move_to_end(tid)
                sid = ev.get("span_id")
                if sid is not None:
                    if sid in self._trace_seen[tid]:
                        continue
                    self._trace_seen[tid].add(sid)
                ev = dict(ev)
                ev["replica"] = tname
                ev["ts_unix_us"] = origin * 1e6 + \
                    float(ev.get("ts_us", 0.0))
                bucket.append(ev)
                self._span_total += 1
                self._m_spans.inc()
            while (len(self._traces) > self.trace_capacity
                   or self._span_total > self.span_capacity) \
                    and self._traces:
                old, dropped = self._traces.popitem(last=False)
                self._trace_seen.pop(old, None)
                self._span_total -= len(dropped)

    def trace_ids(self, limit: int = 100) -> List[dict]:
        with self._lock:
            ids = list(self._traces.items())[-limit:]
        out = []
        for tid, spans in ids:
            root = next((s for s in spans
                         if not s.get("parent_id")), spans[0])
            out.append({"trace_id": tid, "spans": len(spans),
                        "root": root.get("name"),
                        "replicas": sorted({s.get("replica")
                                            for s in spans})})
        return out

    def trace_tree(self, trace_id: str) -> Optional[dict]:
        """The stitched cross-process span list for one trace id
        (prefix match accepted), spans ordered on the absolute
        wall-clock axis and ``ts_us`` REBASED to it so offline
        renderers see one timeline."""
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                for tid, sp in self._traces.items():
                    if tid.startswith(trace_id):
                        trace_id, spans = tid, sp
                        break
            if spans is None:
                return None
            spans = [dict(s) for s in spans]
        spans.sort(key=lambda s: s.get("ts_unix_us", 0.0))
        for s in spans:
            s["ts_us"] = s.get("ts_unix_us", s.get("ts_us", 0.0))
        return {"trace_id": trace_id, "spans": spans}

    # ---- liveness / incidents ----
    def _note_liveness(self, targets, parsed, errors) -> List[str]:
        up_now = set(parsed)
        with self._lock:
            prev_up = set(self._up)
            self._up = up_now
            self._down = dict(errors)
        # death = a member that answered last cycle and now either
        # fails its scrape or vanished from the pool entirely
        return sorted(prev_up - up_now)

    def _note_breach(self, info: dict) -> None:
        # called by SLOMonitor mid-evaluate; defer the bundle pull to
        # the cycle loop so the breach callback stays cheap
        with self._lock:
            self._pending_breach = dict(info)

    def _confirmed_deaths(self, died: List[str]) -> List[str]:
        """An unreachable replica is only a DEATH when the fleet
        agrees it is gone. A member the fleet still declares up is a
        scrape-PATH partition (the collector's hop is dark while the
        router's is fine — the asymmetric case): log and count it,
        never fabricate a replica-death incident bundle from it.
        Serving is untouched, so the incident would be noise that
        buries a real page. Likewise a PLANNED departure — a retire
        or a rollout's replace drained it out on purpose — is churn,
        not a death: paging on it would bury the one incident a
        rolled-back deploy actually writes."""
        if self.fleet is None or not died:
            return died
        fleet_up = {f"replica-{r.id}"
                    for r in self.fleet.snapshot()
                    if getattr(r, "fleet_state", "up") == "up"}
        try:
            planned = {f"replica-{rid}"
                       for rid in self.fleet.departed_rids()}
        except AttributeError:
            planned = set()
        confirmed = []
        for name in died:
            if name in planned:
                logger.info(
                    "fleetobs: %s left the pool by plan (retire/"
                    "replace drain) — churn, not a death; no "
                    "incident", name)
                continue
            if name in fleet_up:
                logger.warning(
                    "fleetobs: %s unreachable on the scrape path "
                    "but the fleet declares it up — asymmetric "
                    "partition, not a death; no incident", name)
                self._m_scrape_partitions.inc()
                continue
            confirmed.append(name)
        return confirmed

    def _check_incidents(self, targets, died: List[str]) -> None:
        died = self._confirmed_deaths(died)
        reason = None
        breached = False
        if self.slo_monitor is not None:
            try:
                breached = self.slo_monitor.any_breached(
                    evaluate=False)
            except Exception:
                breached = False
        with self._lock:
            if breached and not self._breached_prev:
                slo_name = (self._pending_breach
                            or {}).get("slo", "slo")
                reason = f"slo-breach-{slo_name}"
            elif died:
                reason = f"replica-death-{died[0]}"
            self._breached_prev = breached
            self._pending_breach = None
        if reason is None:
            return
        self.write_incident(reason, targets)

    def write_incident(self, reason: str,
                       targets: Optional[List[Tuple[str, str]]] = None
                       ) -> Optional[str]:
        """Pull a bundle from every LIVE member into one incident
        directory with a cross-process MANIFEST. Rate-limited so a
        flapping SLO cannot fill the disk. Returns the directory (or
        None when suppressed)."""
        now = time.time()
        with self._lock:
            if now - self._last_incident_unix \
                    < self.incident_min_interval_s:
                return None
            self._last_incident_unix = now
        if targets is None:
            targets = self._targets()
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:80]
        stamp = time.strftime("%Y%m%d-%H%M%S", time.localtime(now))
        iid = f"incident-{stamp}-{safe}"
        root = os.path.join(self.incident_dir, iid)
        os.makedirs(root, exist_ok=True)
        members: Dict[str, str] = {}
        for tname, url in targets:
            try:
                raw = _http_get(
                    f"{url}/debug/bundle?reason={safe}",
                    max(self.scrape_timeout_s, 5.0))
                payload = json.loads(raw.decode())
                mdir = os.path.join(root, tname)
                os.makedirs(mdir, exist_ok=True)
                for fname, content in (payload.get("files")
                                       or {}).items():
                    fname = os.path.basename(fname)
                    fpath = os.path.join(mdir, fname)
                    with open(fpath, "w", encoding="utf-8") as f:
                        if fname.endswith(".jsonl") \
                                and isinstance(content, list):
                            for ev in content:
                                f.write(json.dumps(ev) + "\n")
                        else:
                            json.dump(content, f, indent=2,
                                      default=str)
                members[tname] = "ok"
            except Exception as e:
                members[tname] = f"error: {e!r}"
        with self._lock:
            recent_traces = list(self._traces)[-16:]
            down = dict(self._down)
        manifest = {
            "incident": iid,
            "reason": reason,
            "ts_unix": now,
            "members": members,
            "targets_down": down,
            "recent_trace_ids": recent_traces,
        }
        if self.slo_monitor is not None:
            try:
                manifest["slo"] = self.slo_monitor.status()
            except Exception:
                pass
        with open(os.path.join(root, "MANIFEST.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, default=str)
        self._m_incidents.inc()
        with self._lock:
            self._incidents.append({"incident": iid,
                                    "reason": reason,
                                    "ts_unix": now,
                                    "dir": root})
        logger.warning("fleet: incident bundle written: %s", root)
        if self.on_incident is not None:
            try:
                self.on_incident(manifest)
            except Exception:
                pass
        return root

    # ---- derived views ----
    def fleet_health(self) -> dict:
        """The router's fleet-health hook: affirmative SLO breaches
        degrade, a dead/stopped collector must NOT (the router treats
        any exception here as 'no fleet signal')."""
        breaches: List[str] = []
        if self.slo_monitor is not None:
            try:
                breaches = [s["name"] for s in
                            self.slo_monitor.status()
                            if s.get("breached")]
            except Exception:
                breaches = []
        with self._lock:
            down = sorted(self._down)
            last = self._last_cycle_unix
        return {"ok": not breaches,
                "slo_breaches": breaches,
                "targets_down": down,
                "last_scrape_unix": last}

    def load_signals(self) -> List[dict]:
        """Per-replica load in the router's ``load_signals`` shape,
        derived from the MERGED per-replica series — the autoscaler
        reads these when wired to the collector. Raises when the last
        successful cycle is stale so the caller falls back to the
        router's direct probes."""
        with self._lock:
            last = self._last_cycle_unix
            up = set(self._up)
        if time.time() - last > max(3 * self.interval_s, 5.0):
            raise RuntimeError("fleet scrape data is stale")
        out: List[dict] = []
        for tname in sorted(up):
            if not tname.startswith("replica-"):
                continue
            rid = tname[len("replica-"):]
            sig = {"rid": rid, "health": "ok", "eligible": True,
                   "queue_depth": 0.0, "inflight": 0.0,
                   "kv_pages_in_use": 0.0, "kv_pages_total": 0.0,
                   "prefix_cache_hits_total": 0.0,
                   "prefix_cache_evictions_total": 0.0}
            for inst in self.registry.collect():
                labels = inst.labels or {}
                if labels.get("replica") != tname:
                    continue
                if inst.name == "serving_gauge":
                    gname = labels.get("name", "")
                    v = inst.value() or 0.0
                    if gname.endswith("_queue_depth"):
                        sig["queue_depth"] += v
                    elif gname.endswith("_slots_in_use"):
                        sig["inflight"] += v
                    elif gname.endswith("_kv_pages_in_use"):
                        sig["kv_pages_in_use"] += v
                    elif gname.endswith("_kv_pages_total"):
                        sig["kv_pages_total"] += v
                elif inst.name == "prefix_cache_hits_total":
                    sig["prefix_cache_hits_total"] += inst.value
                elif inst.name == "prefix_cache_evictions_total":
                    sig["prefix_cache_evictions_total"] += inst.value
            out.append(sig)
        return out

    def replica_raw(self, rids: List[int]) -> Dict[int, dict]:
        """Per-replica raw gate-evidence counters: requests, errors,
        latency bucket counts (edges + counts), and exemplar trace
        ids from the slowest buckets, read off the REPLICA-LABELED
        merged series. Raises when the last successful scrape cycle
        is stale — same discipline as :meth:`cohort_stats`. The
        rollout controller snapshots this when it opens its gate
        window and hands it back as ``cohort_stats(..., since=...)``
        so the comparison covers only window-era traffic."""
        with self._lock:
            last = self._last_cycle_unix
        if time.time() - last > max(3 * self.interval_s, 5.0):
            raise RuntimeError("fleet scrape data is stale")
        want = {f"replica-{int(r)}": int(r) for r in rids}
        out: Dict[int, dict] = {
            int(r): {"requests": 0, "errors": 0, "edges": None,
                     "counts": None, "trace_ids": []}
            for r in rids}
        for inst in self.registry.collect():
            labels = inst.labels or {}
            rid = want.get(labels.get("replica", ""))
            if rid is None:
                continue
            d = out[rid]
            if inst.name == "serving_requests_total":
                d["requests"] += int(inst.value)
            elif inst.name == "serving_errors_total":
                d["errors"] += int(inst.value)
            elif inst.name == "serving_latency_seconds" \
                    and isinstance(inst, Histogram):
                edges, counts, _c, _s = inst.bucket_counts()
                if d["edges"] is None:
                    d["edges"] = list(edges)
                    d["counts"] = [int(c) for c in counts]
                elif d["edges"] == list(edges):
                    for i, c in enumerate(counts):
                        d["counts"][i] += int(c)
                for _i, ex in sorted(
                        getattr(inst, "_exemplars", {}).items(),
                        reverse=True):
                    tid = (ex[0] or {}).get("trace_id") \
                        if isinstance(ex, tuple) else None
                    if tid:
                        d["trace_ids"].append(tid)
        return out

    def cohort_stats(self, cohorts: Dict[str, List[int]],
                     since: Optional[Dict[int, dict]] = None
                     ) -> Dict[str, dict]:
        """Comparative-gate evidence: per cohort (name → replica
        ids), requests/errors summed and latency bucket-merged over
        the members' REPLICA-LABELED serving series, plus up to 8
        exemplar trace ids from the slowest merged buckets. Raises
        when the last successful scrape cycle is stale — the rollout
        controller must HOLD on a dead/stale collector (the
        autoscaler's sensors_ok discipline): promotion needs fresh
        affirmative evidence, and rollback needs fresh affirmative
        evidence too.

        ``since`` (a prior :meth:`replica_raw` snapshot) windows the
        evidence: each member's counters are diffed against its
        snapshot entry before aggregation, so a canary's cold-start
        calls and the incumbents' pre-rollout history drop out and
        both cohorts are compared over the SAME traffic window.
        Members absent from the snapshot (booted after it) count
        from zero, which for rollout cohorts is exactly their
        window-era total."""
        all_rids = sorted({int(r) for rids in cohorts.values()
                           for r in rids})
        raws = self.replica_raw(all_rids)
        out: Dict[str, dict] = {}
        for name, rids in cohorts.items():
            d = {"requests": 0, "errors": 0, "p99_ms": 0.0,
                 "replicas": sorted(int(r) for r in rids),
                 "trace_ids": []}
            edges: Optional[List[float]] = None
            counts: Optional[List[int]] = None
            tids: List[str] = []
            for rid in d["replicas"]:
                raw = raws.get(rid)
                if raw is None:
                    continue
                req, err = raw["requests"], raw["errors"]
                r_counts = raw["counts"]
                prev = (since or {}).get(rid)
                if prev is not None:
                    req = max(0, req - int(prev.get("requests", 0)))
                    err = max(0, err - int(prev.get("errors", 0)))
                    if r_counts is not None \
                            and prev.get("edges") == raw["edges"]:
                        r_counts = [
                            max(0, a - int(b)) for a, b in
                            zip(r_counts, prev.get("counts") or [])]
                d["requests"] += req
                d["errors"] += err
                if r_counts is not None:
                    if edges is None:
                        edges = raw["edges"]
                        counts = list(r_counts)
                    elif edges == raw["edges"]:
                        for i, c in enumerate(r_counts):
                            counts[i] += c
                tids.extend(raw["trace_ids"])
            if edges is not None and counts is not None:
                d["p99_ms"] = round(
                    _hist_quantile(edges, counts, .99) * 1e3, 3)
            d["trace_ids"] = tids[:8]
            out[name] = d
        return out

    def fleet_snapshot(self) -> dict:
        """The JSON dashboard payload ``fleet-status`` renders."""
        with self._lock:
            down = dict(self._down)
            up = sorted(self._up)
            last = self._last_cycle_unix
            cycles = self._cycles
            incidents = list(self._incidents[-8:])
            n_traces = len(self._traces)
            ring = self._ring.items()
            stride = self._ring.stride
        endpoints: Dict[str, dict] = {}
        phases: Dict[str, float] = {}
        for inst in self.registry.collect():
            labels = inst.labels or {}
            if "replica" in labels:
                continue                      # aggregates only
            if inst.name == "serving_latency_seconds" \
                    and isinstance(inst, Histogram):
                ep = labels.get("endpoint", "?")
                edges, counts, count, _ = inst.bucket_counts()
                d = endpoints.setdefault(
                    ep, {"count": 0, "errors": 0,
                         "p50_ms": 0.0, "p99_ms": 0.0})
                d["count"] = count
                d["p50_ms"] = _hist_quantile(edges, counts, .5) * 1e3
                d["p99_ms"] = _hist_quantile(edges, counts, .99) * 1e3
            elif inst.name == "serving_errors_total":
                ep = labels.get("endpoint", "?")
                endpoints.setdefault(
                    ep, {"count": 0, "errors": 0,
                         "p50_ms": 0.0, "p99_ms": 0.0})["errors"] = \
                    int(inst.value)
            elif inst.name == "serving_phase_seconds" \
                    and isinstance(inst, Histogram):
                ph = labels.get("phase", "?")
                edges, counts, _, _ = inst.bucket_counts()
                phases[ph] = max(
                    phases.get(ph, 0.0),
                    _hist_quantile(edges, counts, .99) * 1e3)
        signals = None
        try:
            signals = self.load_signals()
        except Exception:
            pass
        snap = {"ts_unix": last, "cycles": cycles,
                "interval_s": self.interval_s,
                "targets": {t: "up" for t in up},
                "endpoints": endpoints,
                "phases_p99_ms": phases,
                "replicas": signals,
                "incidents": incidents,
                "traces": {"count": n_traces,
                           "recent": self.trace_ids(5)},
                "ring": ring, "ring_stride": stride}
        for t, err in down.items():
            snap["targets"][t] = f"down ({err})"
        if self.slo_monitor is not None:
            try:
                snap["slo"] = self.slo_monitor.status()
            except Exception:
                pass
        try:
            snap["alerts"] = self.alerts.firing()
        except Exception:
            pass
        # per-replica model version + rollout state, read off the
        # in-process router's debug surface: an operator watching
        # fleet-status sees the canary (and which gate it is
        # waiting on) at a glance
        if self.router is not None:
            try:
                fd = self.router.fleet_debug()
            except Exception:
                fd = None
            if fd is not None:
                snap["versions"] = {
                    str(r["id"]): r.get("model_version", 1)
                    for r in fd.get("replicas", [])}
                if fd.get("rollout") is not None:
                    snap["rollout"] = fd["rollout"]
        return snap

    def _append_ring_sample(self, targets, errors) -> None:
        sample = {"ts_unix": time.time(),
                  "up": len(targets) - len(errors),
                  "targets": len(targets)}
        # headline: the busiest aggregate latency family this cycle
        busiest = None
        for inst in self.registry.collect():
            if inst.name != "serving_latency_seconds" \
                    or not isinstance(inst, Histogram) \
                    or "replica" in (inst.labels or {}):
                continue
            if busiest is None or inst.count > busiest.count:
                busiest = inst
        if busiest is not None:
            edges, counts, count, _ = busiest.bucket_counts()
            sample["endpoint"] = \
                (busiest.labels or {}).get("endpoint", "?")
            sample["count"] = count
            sample["p99_ms"] = \
                _hist_quantile(edges, counts, .99) * 1e3
        with self._lock:
            self._ring.append(sample)

    # ---- lifecycle ----
    def start(self) -> "FleetCollector":
        """Open the collector listener and start the scrape loop."""
        from deeplearning4j_tpu.serving.http import (
            _JsonRequestHandler, _make_listener)
        from urllib.parse import urlparse, parse_qs
        collector = self

        class Handler(_JsonRequestHandler):
            def do_GET(self):
                parsed = urlparse(self.path)
                q = parse_qs(parsed.query)
                path = parsed.path
                try:
                    if path == "/metrics":
                        mode = self._metrics_mode()
                        if mode == "openmetrics":
                            self._send_text(
                                200,
                                collector.registry.prometheus_text(
                                    openmetrics=True),
                                "application/openmetrics-text; "
                                "version=1.0.0; charset=utf-8")
                        elif mode == "text":
                            self._send_text(
                                200,
                                collector.registry.prometheus_text(),
                                "text/plain; version=0.0.4; "
                                "charset=utf-8")
                        else:
                            self._send(
                                200, collector.registry.snapshot())
                    elif path == "/healthz":
                        h = collector.fleet_health()
                        h["status"] = "ok" if h["ok"] else "degraded"
                        self._send(200, h)
                    elif path == "/fleet/snapshot":
                        self._send(200, collector.fleet_snapshot())
                    elif path == "/fleet/signals":
                        try:
                            self._send(200,
                                       {"signals":
                                        collector.load_signals()})
                        except RuntimeError as e:
                            self._send(503, {"error": str(e)})
                    elif path == "/traces":
                        limit = int((q.get("limit") or ["100"])[0])
                        self._send(200,
                                   {"traces":
                                    collector.trace_ids(limit)})
                    elif path == "/debug/trace":
                        tid = (q.get("trace_id") or [""])[0]
                        tree = collector.trace_tree(tid) if tid \
                            else None
                        if tree is None:
                            self._send(404,
                                       {"error": "unknown trace id"})
                        else:
                            self._send(200, tree)
                    else:
                        self._send(404, {"error": "not found"})
                except (BrokenPipeError, ConnectionResetError):
                    pass

        httpd = _make_listener(self.host, self.port, Handler)
        http_thread = threading.Thread(
            target=httpd.serve_forever,
            name="fleet-collector-http", daemon=True)
        # a fresh Event per generation: clearing the old one could
        # revive a previous (still-stopping) loop with no handle
        stop_evt = threading.Event()
        thread = threading.Thread(
            target=self._loop, args=(stop_evt,),
            name="fleet-collector", daemon=True)
        with self._lock:
            self._httpd = httpd
            self._http_thread = http_thread
            self._stop_evt = stop_evt
            self._thread = thread
        self.port = httpd.server_address[1]
        http_thread.start()
        thread.start()
        return self

    def _loop(self, stop_evt: threading.Event) -> None:
        while not stop_evt.is_set():
            try:
                self.scrape_once()
            except Exception:
                logger.exception("fleet scrape cycle failed")
            stop_evt.wait(self.interval_s)

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            thread, self._thread = self._thread, None
            httpd, self._httpd = self._httpd, None
            http_thread, self._http_thread = self._http_thread, None
        if thread is not None:
            thread.join(timeout=10.0)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if http_thread is not None:
            http_thread.join(timeout=5.0)
        if self.slo_monitor is not None:
            self.slo_monitor.close()
        self.alerts.stop()


# --------------------------------------------------------------------
# text dashboard
# --------------------------------------------------------------------

def render_status(snap: dict) -> str:
    """``cli.py fleet-status``'s text dashboard over a
    :meth:`FleetCollector.fleet_snapshot` payload."""
    lines: List[str] = []
    ts = snap.get("ts_unix") or 0
    when = time.strftime("%Y-%m-%dT%H:%M:%S",
                         time.localtime(ts)) if ts else "never"
    lines.append(f"fleet-status  (last scrape {when}, "
                 f"interval {snap.get('interval_s', '?')}s, "
                 f"cycles {snap.get('cycles', 0)})")
    targets = snap.get("targets") or {}
    tparts = []
    for name in sorted(targets):
        state = targets[name]
        tparts.append(f"{name} {'UP' if state == 'up' else 'DOWN'}")
    lines.append("members : " + (", ".join(tparts) or "(none)"))
    eps = snap.get("endpoints") or {}
    if eps:
        lines.append("merged latency by endpoint:")
        lines.append(f"  {'endpoint':<14}{'count':>8}{'errors':>8}"
                     f"{'p50 ms':>9}{'p99 ms':>9}")
        for ep in sorted(eps):
            d = eps[ep]
            lines.append(f"  {ep:<14}{d.get('count', 0):>8}"
                         f"{d.get('errors', 0):>8}"
                         f"{d.get('p50_ms', 0.0):>9.2f}"
                         f"{d.get('p99_ms', 0.0):>9.2f}")
    phases = snap.get("phases_p99_ms") or {}
    if phases:
        lines.append("phase p99 (ms): "
                     + "  ".join(f"{k}={v:.2f}"
                                 for k, v in sorted(phases.items())))
    for s in snap.get("slo") or []:
        burns = s.get("burn_rates") or {}
        burn = "  ".join(f"{w}={b:.2f}"
                         for w, b in sorted(burns.items()))
        state = "BREACH" if s.get("breached") else "ok"
        lines.append(f"slo {s.get('name')}: {state}  {burn}")
    reps = snap.get("replicas")
    versions = snap.get("versions") or {}
    if reps:
        for r in reps:
            kvt = r.get("kv_pages_total") or 0
            kv = (100.0 * r.get("kv_pages_in_use", 0) / kvt) \
                if kvt else 0.0
            ver = versions.get(str(r.get("rid")))
            vcol = f" v{ver}" if ver is not None else ""
            lines.append(f"replica {r.get('rid')}:{vcol} "
                         f"queue={r.get('queue_depth', 0):.0f} "
                         f"inflight={r.get('inflight', 0):.0f} "
                         f"kv={kv:.0f}%")
    ro = snap.get("rollout")
    if ro:
        gate = ro.get("last_gate")
        lines.append(
            f"rollout : {ro.get('state', '?')} "
            f"v{ro.get('incumbent_version', '?')}"
            f"->v{ro.get('candidate_version', '?')} "
            f"updated {ro.get('updated', 0)}/{ro.get('total', 0)}"
            + (f"  gate={gate}" if gate else "")
            + (f"  holds={ro.get('holds')}" if ro.get("holds")
               else ""))
    tr = snap.get("traces") or {}
    if tr:
        recent = ", ".join(t["trace_id"][:12]
                           for t in tr.get("recent") or [])
        lines.append(f"traces  : {tr.get('count', 0)} collected"
                     + (f"  recent: {recent}" if recent else ""))
    inc = snap.get("incidents") or []
    if inc:
        lines.append("incidents: "
                     + ", ".join(i["incident"] for i in inc))
    alerts = snap.get("alerts") or []
    if alerts:
        lines.append("alerts  : "
                     + ", ".join(a.get("name", "?") for a in alerts))
    return "\n".join(lines)
