"""Structured tracing: thread-safe nested spans with Chrome export.

The reference's per-iteration visibility is PerformanceListener +
StatsListener timings (optimize/listeners/PerformanceListener.java:
97-119); TensorFlow (arXiv:1605.08695 §5) treats tracing as a
first-class subsystem with a timeline viewer. This module is that
subsystem for the repo: ``with trace.span("data_wait"):`` records a
nested interval, buffered in memory (optionally streamed to JSONL),
exportable to the Chrome trace-event format that Perfetto /
chrome://tracing render directly.

Design constraints, in priority order:

1. **Zero cost when disabled.** ``span()`` on a disabled tracer
   returns a shared no-op singleton — no object allocation, no lock,
   no clock read — so the executors' fit loops can emit spans
   unconditionally. (tests assert the hot path allocates nothing.)
2. Thread safety: spans nest per-thread (a serving worker and the
   training loop interleave without corrupting each other's stacks);
   the event buffer is lock-guarded.
3. Bounded memory: the buffer drops (and counts) events past
   ``buffer_limit`` rather than growing without bound inside a
   long-running server.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "trace", "get_tracer"]


class _NoopSpan:
    """Shared do-nothing context manager handed out while tracing is
    disabled. A singleton: entering/exiting allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, key, value):          # attr API parity with Span
        return self


_NOOP_SPAN = _NoopSpan()


class Span:
    """One timed interval. Use via ``with tracer.span(name):``."""

    __slots__ = ("_tracer", "name", "attrs", "tid", "depth",
                 "t0_ns", "dur_ns")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.tid = 0
        self.depth = 0
        self.t0_ns = 0
        self.dur_ns = 0

    def set(self, key: str, value) -> "Span":
        """Attach an attribute after entry (e.g. a batch size known
        only mid-span)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        self.tid = threading.get_ident()
        self.depth = self._tracer._push()
        self.t0_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ns = time.perf_counter_ns() - self.t0_ns
        self._tracer._pop()
        self._tracer._record(self)
        return False


class Tracer:
    """Buffering span recorder with Chrome trace-event export.

    ``enable()``/``disable()`` flip recording at runtime; while
    disabled every ``span()`` call returns the no-op singleton.
    """

    def __init__(self, enabled: bool = False,
                 buffer_limit: int = 200_000):
        self._enabled = enabled
        self.buffer_limit = buffer_limit
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self.dropped = 0
        self._tls = threading.local()
        self._jsonl: Optional[io.TextIOBase] = None
        # subscribers fed every completed span (the flight recorder's
        # ring); called outside the buffer lock
        self._sinks: List = []
        # one origin for the whole trace so ts values are comparable
        self._origin_ns = time.perf_counter_ns()

    # ---- recording state ----
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, jsonl_path: Optional[str] = None) -> "Tracer":
        """Start recording; with ``jsonl_path`` every completed span
        is also appended to that file as one JSON line (crash-safe
        streaming — the in-memory buffer is still kept for
        ``export_chrome_trace``)."""
        with self._lock:
            if jsonl_path is not None:
                if self._jsonl is not None:
                    self._jsonl.close()
                self._jsonl = open(jsonl_path, "a")
            self._enabled = True
        return self

    def disable(self) -> None:
        with self._lock:
            self._enabled = False
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self.dropped = 0
            self._origin_ns = time.perf_counter_ns()

    # ---- span API ----
    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        """Context manager timing a nested interval. MUST stay
        allocation-free when disabled — the fit loops call this every
        iteration unconditionally."""
        if not self._enabled:
            return _NOOP_SPAN
        return Span(self, name, attrs)

    def instant(self, name: str,
                attrs: Optional[Dict[str, Any]] = None) -> None:
        """A zero-duration marker (e.g. 'xla_compile' from the
        watchdog's monitoring hook)."""
        if not self._enabled:
            return
        s = Span(self, name, attrs)
        s.tid = threading.get_ident()
        s.depth = getattr(self._tls, "depth", 0)
        s.t0_ns = time.perf_counter_ns()
        s.dur_ns = 0
        self._record(s)

    # ---- per-thread nesting ----
    def _push(self) -> int:
        d = getattr(self._tls, "depth", 0)
        self._tls.depth = d + 1
        return d

    def _pop(self) -> None:
        self._tls.depth = max(0, getattr(self._tls, "depth", 1) - 1)

    # ---- storage ----
    def _record(self, span: Span) -> None:
        ev = {"name": span.name,
              "ts_us": (span.t0_ns - self._origin_ns) / 1e3,
              "dur_us": span.dur_ns / 1e3,
              "tid": span.tid,
              "depth": span.depth}
        if span.attrs:
            ev["args"] = dict(span.attrs)
        with self._lock:
            if len(self._events) >= self.buffer_limit:
                self.dropped += 1
            else:
                self._events.append(ev)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(ev) + "\n")
                self._jsonl.flush()
            sinks = list(self._sinks) if self._sinks else None
        if sinks:
            for sink in sinks:
                try:
                    sink(ev)
                except Exception:
                    pass    # a broken sink must not kill the fit loop

    def add_sink(self, fn) -> None:
        """Subscribe ``fn(event_dict)`` to every completed span (only
        while tracing is enabled — disabled tracing records nothing)."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    # ---- export ----
    def export_chrome_trace(self, path: str) -> int:
        """Write the buffered spans as Chrome trace-event JSON
        ("X" complete events; open in Perfetto or chrome://tracing).
        Returns the number of events written."""
        pid = os.getpid()
        out = []
        for ev in self.events():
            rec = {"name": ev["name"], "ph": "X", "pid": pid,
                   "tid": ev["tid"], "ts": ev["ts_us"],
                   "dur": ev["dur_us"]}
            if "args" in ev:
                rec["args"] = ev["args"]
            out.append(rec)
        with open(path, "w") as f:
            json.dump({"traceEvents": out,
                       "displayTimeUnit": "ms",
                       "droppedEvents": self.dropped}, f)
        return len(out)

    def write_jsonl(self, path: str) -> int:
        """Dump the buffer as JSON lines (one span per line)."""
        evs = self.events()
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
        return len(evs)


# The process-wide tracer the executors / serving / CLI share.
trace = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return trace
