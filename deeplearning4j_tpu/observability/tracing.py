"""Structured tracing: thread-safe nested spans with Chrome export,
plus request-scoped distributed tracing for the serving stack.

The reference's per-iteration visibility is PerformanceListener +
StatsListener timings (optimize/listeners/PerformanceListener.java:
97-119); TensorFlow (arXiv:1605.08695 §5) treats tracing as a
first-class subsystem with a timeline viewer. This module is that
subsystem for the repo: ``with trace.span("data_wait"):`` records a
nested interval, buffered in memory (optionally streamed to JSONL),
exportable to the Chrome trace-event format that Perfetto /
chrome://tracing render directly.

Design constraints, in priority order:

1. **Zero cost when disabled.** ``span()`` on a disabled tracer
   returns a shared no-op singleton — no object allocation, no lock,
   no clock read — so the executors' fit loops can emit spans
   unconditionally. (tests assert the hot path allocates nothing.)
2. Thread safety: spans nest per-thread (a serving worker and the
   training loop interleave without corrupting each other's stacks);
   the event buffer is lock-guarded.
3. Bounded memory: the buffer is a ring capped at ``buffer_limit``
   — once full it evicts the oldest event (and counts the
   eviction) rather than growing without bound inside a
   long-running server, so an export holds the newest traces.

Request-scoped tracing (the serving observability PR) adds
:class:`RequestContext`: one trace id minted at HTTP admission (or
adopted from a W3C ``traceparent`` header, so a router→replica hop
keeps the request's identity), carried on the request object through
BatchScheduler queues / ContinuousBatcher slots / worker
crash-restarts, yielding one cross-thread span tree per request::

    request                       (root; the whole HTTP request)
      ├─ admission               (parse + model resolve + submit)
      ├─ queue_wait              (submitted → picked up by the worker)
      ├─ batch_form | prefill    (backend-specific middle phases)
      ├─ device_step | decode
      └─ respond                 (result ready → waiter woken)

Sampling is HEAD-BASED and deterministic in the trace id (a router
tier samples the same 1% everywhere); errored / deadline-exceeded
requests are promoted to sampled so every failure leaves a trace.
Phase durations are recorded on EVERY request (they feed the
``serving_phase_seconds`` histograms and the latency-attribution
report) — only span emission is sampled. Cross-thread handoff is
explicit (``ctx.attach()`` saves and restores the previous
thread-local state on exit, so a pooled worker thread can never leak
one request's context into the next).
"""

from __future__ import annotations

import collections
import io
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "trace", "get_tracer",
           "RequestContext", "Sampler", "current_context"]


class _NoopSpan:
    """Shared do-nothing context manager handed out while tracing is
    disabled. A singleton: entering/exiting allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, key, value):          # attr API parity with Span
        return self


_NOOP_SPAN = _NoopSpan()


# id generation: trace/span ids are correlation keys, not secrets —
# a per-thread PRNG seeded once from the OS beats an os.urandom
# syscall per id by ~30x on the serving hot path (ids are minted per
# request and per span)
_ID_TLS = threading.local()


def _id_rng():
    rng = getattr(_ID_TLS, "rng", None)
    if rng is None:
        import random
        rng = _ID_TLS.rng = random.Random(os.urandom(16))
    return rng


def _new_trace_id() -> str:
    return f"{_id_rng().getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


class Span:
    """One timed interval. Use via ``with tracer.span(name):``."""

    __slots__ = ("_tracer", "name", "attrs", "tid", "depth",
                 "t0_ns", "dur_ns", "trace_id", "span_id",
                 "parent_id")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.tid = 0
        self.depth = 0
        self.t0_ns = 0
        self.dur_ns = 0
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_id: Optional[str] = None

    def set(self, key: str, value) -> "Span":
        """Attach an attribute after entry (e.g. a batch size known
        only mid-span)."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        self.tid = threading.get_ident()
        self.depth = self._tracer._push()
        self.t0_ns = time.perf_counter_ns()
        # sinks (the flight recorder) learn about the span at OPEN so
        # a bundle dumped mid-span can list it as unclosed; span ids
        # are minted only when someone is listening or the span rides
        # a request trace — the no-sink hot path stays id-free
        if self._tracer._sinks or self.trace_id is not None:
            if self.span_id is None:
                self.span_id = _new_span_id()
            self._tracer._notify_open(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur_ns = time.perf_counter_ns() - self.t0_ns
        if exc_type is not None:
            self.set("error", exc_type.__name__)
        self._tracer._pop()
        self._tracer._record(self)
        return False


class Tracer:
    """Buffering span recorder with Chrome trace-event export.

    ``enable()``/``disable()`` flip recording at runtime; while
    disabled every ``span()`` call returns the no-op singleton.
    """

    def __init__(self, enabled: bool = False,
                 buffer_limit: int = 200_000):
        self._enabled = enabled
        self.buffer_limit = buffer_limit
        self._lock = threading.Lock()
        # ring, not list: request spans are recorded even while the
        # tracer is disabled (sampling gates them, not ``--trace``),
        # so a long-running server must evict OLDEST once full — an
        # export should hold the most recent traces, and memory stays
        # bounded at buffer_limit either way
        self._events: collections.deque = collections.deque(
            maxlen=buffer_limit)
        self.dropped = 0
        self._tls = threading.local()
        self._jsonl: Optional[io.TextIOBase] = None
        # subscribers fed every completed span (the flight recorder's
        # ring); called outside the buffer lock
        self._sinks: List = []
        # one origin for the whole trace so ts values are comparable
        self._origin_ns = time.perf_counter_ns()
        # wall-clock anchor for the same origin: a cross-process
        # collector needs absolute time to order spans from different
        # tracers (perf_counter origins are per-process and arbitrary)
        self._origin_unix = time.time()
        # monotone per-event sequence number; the cursor a remote
        # drain (``export_since``) resumes from, immune to ring
        # eviction (unlike buffer indices)
        self._seq = 0

    # ---- recording state ----
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, jsonl_path: Optional[str] = None) -> "Tracer":
        """Start recording; with ``jsonl_path`` every completed span
        is also appended to that file as one JSON line (crash-safe
        streaming — the in-memory buffer is still kept for
        ``export_chrome_trace``)."""
        with self._lock:
            if jsonl_path is not None:
                if self._jsonl is not None:
                    self._jsonl.close()
                self._jsonl = open(jsonl_path, "a")
            self._enabled = True
        return self

    def disable(self) -> None:
        with self._lock:
            self._enabled = False
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._origin_ns = time.perf_counter_ns()
            self._origin_unix = time.time()
            self._seq = 0

    # ---- span API ----
    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        """Context manager timing a nested interval. MUST stay
        allocation-free when disabled — the fit loops call this every
        iteration unconditionally."""
        if not self._enabled:
            return _NOOP_SPAN
        return Span(self, name, attrs)

    def instant(self, name: str,
                attrs: Optional[Dict[str, Any]] = None) -> None:
        """A zero-duration marker (e.g. 'xla_compile' from the
        watchdog's monitoring hook)."""
        if not self._enabled:
            return
        s = Span(self, name, attrs)
        s.tid = threading.get_ident()
        s.depth = getattr(self._tls, "depth", 0)
        s.t0_ns = time.perf_counter_ns()
        s.dur_ns = 0
        self._record(s)

    # ---- request-scoped recording ----
    def record_span(self, name: str, t0_ns: int, dur_ns: int, *,
                    trace_id: Optional[str] = None,
                    span_id: Optional[str] = None,
                    parent_id: Optional[str] = None,
                    attrs: Optional[Dict[str, Any]] = None,
                    tid: Optional[int] = None) -> str:
        """Record one completed span from explicit timestamps — the
        request-phase path, where a phase starts on one thread and
        ends on another so a ``with`` block cannot time it. Records
        regardless of the global enable switch: request spans are
        gated by the head-sampling decision, not ``--trace``."""
        s = Span(self, name, dict(attrs) if attrs else None)
        s.tid = tid if tid is not None else threading.get_ident()
        s.t0_ns = t0_ns
        s.dur_ns = dur_ns
        s.trace_id = trace_id
        s.span_id = span_id or _new_span_id()
        s.parent_id = parent_id
        self._record(s)
        return s.span_id

    def notify_request_open(self, name: str, t0_ns: int, *,
                            trace_id: str, span_id: str,
                            parent_id: Optional[str] = None,
                            attrs: Optional[Dict[str, Any]] = None
                            ) -> None:
        """Span-open notification for a request's root span: admission
        tells the sinks a request is in flight, so a crash bundle can
        list it unclosed even though its close span never happened."""
        s = Span(self, name, dict(attrs) if attrs else None)
        s.tid = threading.get_ident()
        s.t0_ns = t0_ns
        s.trace_id, s.span_id, s.parent_id = trace_id, span_id, \
            parent_id
        self._notify_open(s)

    @property
    def origin_ns(self) -> int:
        return self._origin_ns

    # ---- per-thread nesting ----
    def _push(self) -> int:
        d = getattr(self._tls, "depth", 0)
        self._tls.depth = d + 1
        return d

    def _pop(self) -> None:
        self._tls.depth = max(0, getattr(self._tls, "depth", 1) - 1)

    # ---- storage ----
    def _span_ids(self, span: Span, ev: dict) -> None:
        if span.trace_id is not None:
            ev["trace_id"] = span.trace_id
        if span.span_id is not None:
            ev["span_id"] = span.span_id
        if span.parent_id is not None:
            ev["parent_id"] = span.parent_id

    def _notify_open(self, span: Span) -> None:
        """Span-open event to the sinks ONLY (never the buffer): the
        flight recorder tracks open spans so a crash-time bundle can
        include work still in flight with an ``unclosed`` marker."""
        with self._lock:
            sinks = list(self._sinks) if self._sinks else None
        if not sinks:
            return
        ev = {"ph": "open", "name": span.name,
              "ts_us": (span.t0_ns - self._origin_ns) / 1e3,
              "tid": span.tid}
        self._span_ids(span, ev)
        if span.attrs:
            ev["args"] = dict(span.attrs)
        for sink in sinks:
            try:
                sink(ev)
            except Exception:
                pass

    def _record(self, span: Span) -> None:
        ev = {"name": span.name,
              "ts_us": (span.t0_ns - self._origin_ns) / 1e3,
              "dur_us": span.dur_ns / 1e3,
              "tid": span.tid,
              "depth": span.depth}
        self._span_ids(span, ev)
        if span.attrs:
            ev["args"] = dict(span.attrs)
        with self._lock:
            if len(self._events) == self.buffer_limit:
                # ring is full: the append below evicts the oldest
                self.dropped += 1
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(ev) + "\n")
                self._jsonl.flush()
            sinks = list(self._sinks) if self._sinks else None
        if sinks:
            for sink in sinks:
                try:
                    sink(ev)
                except Exception:
                    pass    # a broken sink must not kill the fit loop

    def add_sink(self, fn) -> None:
        """Subscribe ``fn(event_dict)`` to every completed span (only
        while tracing is enabled — disabled tracing records nothing)."""
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def export_since(self, since: int = 0,
                     limit: int = 10_000) -> Dict[str, Any]:
        """Incremental drain for a remote collector: every buffered
        span with ``seq > since``, oldest first, capped at ``limit``
        per call. The returned ``next`` is the cursor to pass back on
        the following poll; ``origin_unix`` lets the collector map a
        span's process-relative ``ts_us`` onto wall-clock time
        (``origin_unix * 1e6 + ts_us``) so spans from N processes
        order on one axis. If the ring evicted events past the
        caller's cursor (a slow scraper), the gap shows up as
        ``dropped`` growth — the collector reports it, it does not
        stall."""
        since = int(since)
        with self._lock:
            spans = [ev for ev in self._events
                     if ev.get("seq", 0) > since]
            dropped = self.dropped
            origin_unix = self._origin_unix
            head = self._seq
        spans = spans[:max(0, int(limit))]
        nxt = spans[-1]["seq"] if spans else max(since, 0)
        # ``head`` is the newest seq this process has assigned: a
        # collector whose cursor exceeds it knows the process (and
        # its seq space) restarted and resyncs from zero
        return {"origin_unix": origin_unix, "next": nxt,
                "head": head, "dropped": dropped, "spans": spans}

    def events_for_trace(self, trace_id: str) -> List[dict]:
        """Every buffered span carrying ``trace_id`` — the hop
        verification a fleet soak asserts on: one trace id must span
        the router's root request span AND the replica spans it
        parented via the forwarded ``traceparent`` header (including
        every failed-over attempt)."""
        with self._lock:
            return [ev for ev in self._events
                    if ev.get("trace_id") == trace_id]

    # ---- export ----
    def export_chrome_trace(self, path: str) -> int:
        """Write the buffered spans as Chrome trace-event JSON
        ("X" complete events; open in Perfetto or chrome://tracing).
        Returns the number of events written."""
        pid = os.getpid()
        out = []
        for ev in self.events():
            rec = {"name": ev["name"], "ph": "X", "pid": pid,
                   "tid": ev["tid"], "ts": ev["ts_us"],
                   "dur": ev["dur_us"]}
            args = dict(ev.get("args") or {})
            # trace ids ride the args so Perfetto (and
            # tools/trace_report.py) can group spans per request
            for k in ("trace_id", "span_id", "parent_id"):
                if k in ev:
                    args[k] = ev[k]
            if args:
                rec["args"] = args
            out.append(rec)
        with open(path, "w") as f:
            json.dump({"traceEvents": out,
                       "displayTimeUnit": "ms",
                       "droppedEvents": self.dropped}, f)
        return len(out)

    def write_jsonl(self, path: str) -> int:
        """Dump the buffer as JSON lines (one span per line)."""
        evs = self.events()
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
        return len(evs)


# The process-wide tracer the executors / serving / CLI share.
trace = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return trace


# ---------------------------------------------------------------------------
# request-scoped distributed tracing
# ---------------------------------------------------------------------------

# W3C trace context: version-traceid-spanid-flags
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

_REQ_TLS = threading.local()


def current_context() -> Optional["RequestContext"]:
    """The RequestContext attached to this thread (via
    ``ctx.attach()``), or None."""
    return getattr(_REQ_TLS, "ctx", None)


class _Attach:
    """Context manager installing a RequestContext as the thread's
    current context. Exit ALWAYS restores the previous value — a
    pooled worker thread reused across requests can never leak one
    request's context into the next."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: "RequestContext"):
        self.ctx = ctx
        self._prev = None

    def __enter__(self) -> "RequestContext":
        self._prev = getattr(_REQ_TLS, "ctx", None)
        _REQ_TLS.ctx = self.ctx
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        _REQ_TLS.ctx = self._prev
        return False


class Sampler:
    """Head-based sampling policy: one default rate plus per-route
    overrides. The decision is a pure function of the trace id, so
    every replica behind a router samples the SAME 1% — a sampled
    trace is sampled end to end across the fleet."""

    def __init__(self, rate: float = 0.01,
                 routes: Optional[Dict[str, float]] = None):
        self.rate = float(rate)
        self.routes = dict(routes or {})

    def rate_for(self, route: Optional[str]) -> float:
        if route is not None and route in self.routes:
            return float(self.routes[route])
        return self.rate

    def sample(self, trace_id: str,
               route: Optional[str] = None) -> bool:
        r = self.rate_for(route)
        if r >= 1.0:
            return True
        if r <= 0.0:
            return False
        # the LOW 32 bits of the trace id as a uniform in [0, 1):
        # W3C/OTel only guarantee randomness in the rightmost 7
        # bytes (the high bits may carry a timestamp in X-Ray-style
        # ids), so keying on them would make adopted-trace sampling
        # all-or-nothing behind some routers
        return int(trace_id[-8:], 16) / float(0x100000000) < r


class RequestContext:
    """One request's identity + timing as it crosses threads.

    Carries the W3C-compatible trace id, the root span of the local
    span tree, the head-sampling decision, the deadline, and the
    per-phase duration ledger. Phases are CONTIGUOUS segments: each
    ``phase_done(name)`` closes the segment begun by the previous
    mark, so the phase durations always sum to exactly the wall time
    from admission to the last mark — the attribution report
    reconciles against the whole-request histogram by construction.
    """

    __slots__ = ("trace_id", "root_span_id", "parent_id", "sampled",
                 "route", "deadline", "t0_ns", "t0_wall", "phases",
                 "_phase", "_last_ns", "_lock", "error", "tracer",
                 "_finished", "attrs")

    def __init__(self, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 sampled: bool = False,
                 route: Optional[str] = None,
                 deadline: Optional[float] = None,
                 tracer: Optional[Tracer] = None):
        self.trace_id = trace_id or _new_trace_id()
        self.root_span_id = _new_span_id()
        self.parent_id = parent_id
        self.sampled = bool(sampled)
        self.route = route
        self.deadline = deadline          # time.monotonic() terms
        self.tracer = tracer if tracer is not None else trace
        self.t0_ns = time.perf_counter_ns()
        self.t0_wall = time.time()
        self.phases: Dict[str, float] = {}
        self._phase: Optional[str] = "admission"
        self._last_ns = self.t0_ns
        self._lock = threading.Lock()
        self.error: Optional[str] = None
        self._finished = False
        self.attrs: Dict[str, Any] = {}

    # ---- construction helpers ----
    @classmethod
    def new(cls, route: str, sampler: Optional[Sampler] = None,
            deadline: Optional[float] = None,
            tracer: Optional[Tracer] = None) -> "RequestContext":
        """Mint a fresh context at admission; the sampling decision is
        made HERE (head-based), derived from the new trace id."""
        tid = _new_trace_id()
        sampled = sampler.sample(tid, route) if sampler else False
        return cls(trace_id=tid, sampled=sampled, route=route,
                   deadline=deadline, tracer=tracer)

    @classmethod
    def from_traceparent(cls, header: Optional[str], route: str,
                         sampler: Optional[Sampler] = None,
                         deadline: Optional[float] = None,
                         tracer: Optional[Tracer] = None
                         ) -> Optional["RequestContext"]:
        """Adopt an upstream trace (router→replica hop): keep its
        trace id, parent the local root span to the caller's span,
        and honour its sampled flag OR our own head decision (an
        upstream that sampled the request keeps it sampled here).
        Malformed headers return None — mint fresh instead."""
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if not m or m.group(1) == "ff":
            return None
        trace_id, parent_span, flags = m.group(2), m.group(3), \
            m.group(4)
        if trace_id == "0" * 32 or parent_span == "0" * 16:
            return None
        sampled = bool(int(flags, 16) & 0x01)
        if not sampled and sampler is not None:
            sampled = sampler.sample(trace_id, route)
        return cls(trace_id=trace_id, parent_id=parent_span,
                   sampled=sampled, route=route, deadline=deadline,
                   tracer=tracer)

    def traceparent(self) -> str:
        """The W3C header value naming THIS context's root span as
        the parent for the next hop."""
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.root_span_id}-{flags}"

    # ---- cross-thread handoff ----
    def attach(self) -> _Attach:
        """``with ctx.attach():`` — make this the thread's current
        context for the block. Explicit, and always restored on exit
        (no thread-local leakage across pool reuse)."""
        return _Attach(self)

    # ---- phase ledger ----
    def phase_done(self, name: str,
                   now_in: Optional[str] = None,
                   attrs: Optional[Dict[str, Any]] = None) -> float:
        """Close the contiguous segment begun by the previous mark as
        phase ``name``; returns its duration in seconds. ``now_in``
        labels the phase the request is in NEXT (what
        ``/debug/requests`` shows for in-flight work). Emits a span
        (parented to the request root) when sampled; updates the
        ledger ALWAYS."""
        now = time.perf_counter_ns()
        with self._lock:
            t0, self._last_ns = self._last_ns, now
            dur_ns = now - t0
            dur_s = dur_ns / 1e9
            self.phases[name] = self.phases.get(name, 0.0) + dur_s
            self._phase = now_in
        if self.sampled:
            try:
                self.tracer.record_span(
                    name, t0, dur_ns, trace_id=self.trace_id,
                    parent_id=self.root_span_id, attrs=attrs)
            except Exception:
                pass      # tracing must never fail the request
        return dur_s

    def phase(self, name: str,
              now_in: Optional[str] = None) -> "_PhaseBlock":
        """``with ctx.phase("device_step"):`` for phases that start
        and end on one thread."""
        return _PhaseBlock(self, name, now_in)

    def set_phase(self, name: Optional[str]) -> None:
        with self._lock:
            self._phase = name

    def current_phase(self) -> Optional[str]:
        with self._lock:
            return self._phase

    # ---- error promotion & completion ----
    def set_error(self, exc: BaseException) -> None:
        """Record the failure AND promote the request to sampled —
        every error / deadline-exceeded request leaves a trace."""
        with self._lock:
            if self.error is None:
                self.error = repr(exc)[:300]
        self.sampled = True

    def open_root(self, attrs: Optional[Dict[str, Any]] = None
                  ) -> None:
        """Announce the root span to the tracer sinks at admission so
        a crash bundle lists this request as an unclosed span."""
        if not self.sampled:
            return
        try:
            self.tracer.notify_request_open(
                "request", self.t0_ns, trace_id=self.trace_id,
                span_id=self.root_span_id, parent_id=self.parent_id,
                attrs=dict(attrs or {},
                           route=self.route) if (attrs or self.route)
                else None)
        except Exception:
            pass

    def finish(self, attrs: Optional[Dict[str, Any]] = None) -> float:
        """Close the request: emits the root ``request`` span (when
        sampled) carrying route / phase ledger / error; returns total
        wall seconds. Idempotent."""
        now = time.perf_counter_ns()
        with self._lock:
            if self._finished:
                return (self._last_ns - self.t0_ns) / 1e9
            self._finished = True
            if now > self._last_ns:
                # whatever ran since the last mark (response
                # serialization + socket write) becomes the terminal
                # segment, so the ledger still sums to the total
                tail = (now - self._last_ns) / 1e9
                self.phases["finalize"] = \
                    self.phases.get("finalize", 0.0) + tail
                self._last_ns = now
            total_ns = self._last_ns - self.t0_ns
            phases = {k: round(v, 6) for k, v in self.phases.items()}
            self._phase = None
        if self.sampled:
            a: Dict[str, Any] = {"route": self.route,
                                 "phases": phases}
            if self.error is not None:
                a["error"] = self.error
            if self.attrs:
                a.update(self.attrs)
            if attrs:
                a.update(attrs)
            try:
                self.tracer.record_span(
                    "request", self.t0_ns, total_ns,
                    trace_id=self.trace_id,
                    span_id=self.root_span_id,
                    parent_id=self.parent_id, attrs=a)
            except Exception:
                pass
        return total_ns / 1e9

    # ---- introspection (/debug/requests) ----
    def age_s(self) -> float:
        return (time.perf_counter_ns() - self.t0_ns) / 1e9

    def deadline_remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def to_debug(self) -> dict:
        with self._lock:
            phases = {k: round(v * 1e3, 3)
                      for k, v in self.phases.items()}
            phase = self._phase
        out = {"trace_id": self.trace_id, "route": self.route,
               "sampled": self.sampled, "phase": phase,
               "age_ms": round(self.age_s() * 1e3, 3),
               "phases_ms": phases}
        rem = self.deadline_remaining_s()
        if rem is not None:
            out["deadline_remaining_ms"] = round(rem * 1e3, 3)
        if self.error is not None:
            out["error"] = self.error
        return out


class _PhaseBlock:
    __slots__ = ("_ctx", "_name", "_now_in")

    def __init__(self, ctx: RequestContext, name: str,
                 now_in: Optional[str]):
        self._ctx = ctx
        self._name = name
        self._now_in = now_in

    def __enter__(self) -> RequestContext:
        self._ctx.set_phase(self._name)
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        if exc is not None:
            self._ctx.set_error(exc)
        self._ctx.phase_done(self._name, now_in=self._now_in)
        return False
