"""Observability subsystem: tracing, recompile watchdog, unified
metrics registry, training-step profiler.

The measurement substrate under every perf claim in this repo (the
reference's PerformanceListener/StatsStorage pipeline, grown into the
tracing + compile/runtime-attribution subsystem TensorFlow
(arXiv:1605.08695) treats as first-class):

- ``tracing``        nested spans -> JSONL / Chrome trace (Perfetto)
- ``compile_watch``  every XLA compile logged with shapes; cache
                     hit/miss accounting; recompile-storm trip-wire
- ``registry``       process-wide counters/gauges/histograms with
                     Prometheus text exposition
- ``step_profile``   data-wait / dispatch / device decomposition +
                     MFU, riding the standard listener chain

and (ISSUE 3) the layer that WATCHES the measurements and acts:

- ``health``           HealthMonitor: fused in-step finite check +
                       host sliding-window detectors, with
                       warn/raise/rollback policies
- ``flight_recorder``  bounded event ring -> self-contained
                       post-mortem bundle on anomaly/crash/dump()
- ``alerts``           declarative threshold rules over any registry
                       metric (for-duration + debounce), feeding
                       /healthz and the UI health panel
"""

from deeplearning4j_tpu.observability.alerts import (
    AlertManager, AlertRule,
)
from deeplearning4j_tpu.observability.compile_watch import (
    CompileWatcher, RecompileStormError, install_global_watch, watch,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    FlightRecorder,
)
from deeplearning4j_tpu.observability.health import (
    HealthMonitor, TrainingDivergedError, fused_health,
)
from deeplearning4j_tpu.observability.registry import (
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
)
from deeplearning4j_tpu.observability.slo import (
    SLO, BurnWindow, SLOMonitor,
)
from deeplearning4j_tpu.observability.step_profile import (
    ProfilerListener, detect_peak_flops, model_flops_utilization,
    peak_flops_for_kind,
)
from deeplearning4j_tpu.observability.tracing import (
    RequestContext, Sampler, Tracer, current_context, get_tracer,
    trace,
)

__all__ = [
    "AlertManager", "AlertRule", "CompileWatcher",
    "FlightRecorder", "HealthMonitor", "RecompileStormError",
    "TrainingDivergedError", "fused_health", "install_global_watch",
    "watch", "REGISTRY", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "ProfilerListener", "detect_peak_flops",
    "model_flops_utilization", "peak_flops_for_kind", "Tracer",
    "get_tracer", "trace", "RequestContext", "Sampler",
    "current_context", "SLO", "BurnWindow", "SLOMonitor",
]
