"""Unified metrics registry: counters, gauges, histograms, Prometheus.

One process-wide pipe for every subsystem's numbers. Before this
module, metrics code was scattered: ``train/listeners.py`` logged,
``ui/stats.py`` stored, ``serving/metrics.py`` owned its own
histogram/quantile code. The histogram here IS that code, lifted out
of serving so training and serving share one implementation, plus the
Prometheus text exposition every scraper expects.

Metrics are keyed by (name, labels): ``registry.counter("x_total",
labels={"endpoint": "predict"})`` is get-or-create, so concurrent
callers converge on one instrument. ``prometheus_text()`` renders the
standard exposition format (# TYPE/# HELP headers, cumulative
``_bucket`` counts with ``le`` labels, ``_sum``/``_count``).
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "REGISTRY", "default_latency_buckets", "safe_inc"]


def default_latency_buckets(lo: float = 1e-4, hi: float = 60.0,
                            factor: float = 1.45) -> List[float]:
    """Log-spaced bucket edges in seconds (the serving latency
    default: O(1) recording, quantiles interpolated in-bucket)."""
    edges = [lo]
    while edges[-1] < hi:
        edges.append(edges[-1] * factor)
    return edges


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sane_name(name: str) -> str:
    """Coerce to a legal Prometheus metric name."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Optional[Dict[str, str]],
                extra: Optional[Dict[str, str]] = None) -> str:
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if v != v:                                    # NaN
        return "NaN"
    if v in (math.inf, -math.inf):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self, openmetrics: bool = False) -> List[str]:
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(self.value)}"]


class Gauge(_Metric):
    """Settable value OR pull callback sampled at exposition time
    (queue depths must be read when scraped, not when registered)."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None,
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, help, labels)
        self._value = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def value(self) -> Optional[float]:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return None        # a dead callback must not kill /metrics
        with self._lock:
            return self._value

    def expose(self, openmetrics: bool = False) -> List[str]:
        v = self.value()
        if v is None:
            return []
        return [f"{self.name}{_fmt_labels(self.labels)} "
                f"{_fmt_value(v)}"]


class Histogram(_Metric):
    """Fixed-bucket histogram with interpolated quantiles — the code
    previously private to ``serving/metrics.py``, now shared.
    Recording is O(#buckets) scan + one locked multi-field update."""

    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Optional[List[float]] = None):
        super().__init__(name, help, labels)
        self.edges = list(buckets) if buckets is not None \
            else default_latency_buckets()
        self.counts = [0] * (len(self.edges) + 1)   # +1 overflow
        self.count = 0
        self.sum = 0.0
        # per-bucket exemplars: bucket index -> (labels, value, unix
        # ts). An exemplar links an aggregate bucket back to ONE
        # concrete observation (a sampled trace id), so a p99 spike
        # on a dashboard resolves to a trace in the flight recorder.
        self._exemplars: Dict[int, Tuple[Dict[str, str], float,
                                         float]] = {}

    def record(self, v: float,
               exemplar: Optional[Dict[str, str]] = None) -> None:
        i = 0
        edges = self.edges
        while i < len(edges) and v > edges[i]:
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            if exemplar:
                self._exemplars[i] = (dict(exemplar), float(v),
                                      time.time())

    # alias matching prometheus client naming
    observe = record

    def bucket_counts(self) -> Tuple[List[float], List[int], int,
                                     float]:
        """Consistent snapshot of ``(edges, counts, count, sum)`` —
        the SLO layer derives good/total counts from the buckets."""
        with self._lock:
            return (list(self.edges), list(self.counts), self.count,
                    self.sum)

    def exemplars(self) -> List[dict]:
        """Current per-bucket exemplars: ``{le, labels, value, ts}``
        (``le`` is the bucket's upper edge; ``inf`` for overflow)."""
        with self._lock:
            items = sorted(self._exemplars.items())
        out = []
        for i, (labels, v, ts) in items:
            le = self.edges[i] if i < len(self.edges) else math.inf
            out.append({"le": le, "labels": labels, "value": v,
                        "ts": ts})
        return out

    def quantile(self, q: float) -> float:
        """Approximate quantile: linear interpolation inside the
        bucket holding the q-th sample (0 if empty)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0
        edges = self.edges
        for i, c in enumerate(counts):
            if seen + c >= rank:
                lo = 0.0 if i == 0 else edges[i - 1]
                hi = edges[min(i, len(edges) - 1)]
                frac = (rank - seen) / c if c else 0.0
                return lo + (hi - lo) * min(1.0, frac)
            seen += c
        return edges[-1]

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
        return {"count": count,
                "sum": total,
                "p50": self.quantile(0.50),
                "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    @staticmethod
    def _exemplar_suffix(ex) -> str:
        # OpenMetrics exemplar syntax: `... # {trace_id="abc"} v ts`.
        # Exemplars are ONLY legal in the OpenMetrics exposition —
        # the classic text format (text/plain; version=0.0.4) allows
        # nothing after the value but an integer timestamp, and a real
        # Prometheus scrape of a classic payload with this tail fails
        # to parse ENTIRELY — so expose() emits it only when asked
        # for openmetrics output.
        if ex is None:
            return ""
        labels, v, ts = ex
        return (f" # {_fmt_labels(None, labels)} {_fmt_value(v)} "
                f"{ts:.3f}")

    def expose(self, openmetrics: bool = False) -> List[str]:
        with self._lock:
            counts = list(self.counts)
            count, total = self.count, self.sum
            exemplars = (dict(self._exemplars) if openmetrics else {})
        out = []
        cum = 0
        for i, (edge, c) in enumerate(zip(self.edges, counts)):
            cum += c
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.labels, {'le': f'{edge:.6g}'})}"
                f" {cum}"
                f"{self._exemplar_suffix(exemplars.get(i))}")
        out.append(f"{self.name}_bucket"
                   f"{_fmt_labels(self.labels, {'le': '+Inf'})}"
                   f" {count}"
                   f"{self._exemplar_suffix(exemplars.get(len(self.edges)))}")
        out.append(f"{self.name}_sum{_fmt_labels(self.labels)} "
                   f"{_fmt_value(total)}")
        out.append(f"{self.name}_count{_fmt_labels(self.labels)} "
                   f"{count}")
        return out


def _key(name: str,
         labels: Optional[Dict[str, str]]) -> Tuple[str, tuple]:
    return (name, tuple(sorted((labels or {}).items())))


class MetricsRegistry:
    """Get-or-create instrument store with Prometheus exposition.

    One process-wide instance (``REGISTRY``) is the default pipe;
    subsystems that need isolation (each ``ServingMetrics`` in a test
    suite) instantiate their own.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], _Metric] = {}

    def _get_or_create(self, cls, name, help, labels, **kw):
        name = _sane_name(name)
        k = _key(name, labels)
        with self._lock:
            m = self._metrics.get(k)
            if m is None:
                m = cls(name, help=help, labels=labels, **kw)
                self._metrics[k] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r}{labels!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get_or_create(Gauge, name, help, labels)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[List[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def register(self, metric: _Metric) -> _Metric:
        """Adopt an externally-constructed instrument (e.g. serving's
        LatencyHistogram subclass) into this registry's exposition."""
        metric.name = _sane_name(metric.name)
        k = _key(metric.name, metric.labels)
        with self._lock:
            existing = self._metrics.get(k)
            if existing is not None and existing is not metric:
                raise ValueError(
                    f"metric {metric.name!r}{metric.labels!r} already "
                    "registered")
            self._metrics[k] = metric
        return metric

    def adopt(self, metric: _Metric) -> _Metric:
        """Get-or-register for externally-constructed instruments:
        atomically returns the already-registered instrument for this
        (name, labels) if one exists, else registers ``metric``. The
        shared-registry analogue of counter()/gauge()'s get-or-create
        — concurrent constructors converge on one instrument instead
        of racing register() into a ValueError."""
        metric.name = _sane_name(metric.name)
        k = _key(metric.name, metric.labels)
        with self._lock:
            existing = self._metrics.get(k)
            if existing is not None:
                return existing
            self._metrics[k] = metric
            return metric

    def unregister(self, name: str,
                   labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._metrics.pop(_key(_sane_name(name), labels), None)

    def get(self, name: str,
            labels: Optional[Dict[str, str]] = None):
        with self._lock:
            return self._metrics.get(_key(_sane_name(name), labels))

    def collect(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> dict:
        """JSON-friendly dump (name{labels} -> value/summary)."""
        out = {}
        for m in self.collect():
            key = m.name + _fmt_labels(m.labels)
            if isinstance(m, Counter):
                out[key] = m.value
            elif isinstance(m, Gauge):
                out[key] = m.value()
            elif isinstance(m, Histogram):
                out[key] = m.snapshot()
        return out

    def prometheus_text(self, openmetrics: bool = False) -> str:
        """The standard exposition format (text/plain; version=0.0.4),
        or — with ``openmetrics=True`` — OpenMetrics text
        (application/openmetrics-text): same families plus per-bucket
        exemplars and the mandatory ``# EOF`` terminator. Exemplars
        are NOT emitted in the classic format, where they are a
        parse error that would kill the whole scrape. Families are
        grouped so a name shared by many label sets gets one # TYPE
        header."""
        families: Dict[str, List[_Metric]] = {}
        order: List[str] = []
        for m in self.collect():
            if m.name not in families:
                families[m.name] = []
                order.append(m.name)
            families[m.name].append(m)
        lines: List[str] = []
        for name in order:
            members = families[name]
            head = members[0]
            family = name
            if openmetrics and head.kind == "counter" \
                    and family.endswith("_total"):
                # OpenMetrics counter families are named WITHOUT the
                # _total suffix (the sample keeps it); declaring the
                # family as `foo_total` makes the bare `foo_total`
                # sample a clashing name that strict parsers reject,
                # killing the whole scrape
                family = family[:-len("_total")]
            if head.help:
                lines.append(f"# HELP {family} {head.help}")
            lines.append(f"# TYPE {family} {head.kind}")
            for m in members:
                lines.extend(m.expose(openmetrics=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + ("\n" if lines else "")


# The process-wide default registry (training, compile watchdog,
# ParallelInference). Serving stacks default to per-instance
# registries so parallel test servers don't share counters; pass
# ``registry=REGISTRY`` to join the global pipe.
REGISTRY = MetricsRegistry()


def safe_inc(name: str, help: str = "",
             labels: Optional[Dict[str, str]] = None,
             registry: Optional[MetricsRegistry] = None) -> None:
    """Best-effort counter increment (default: the process-wide
    registry): NEVER raises — instrumentation on a failure path must
    not take down the path it measures. The one copy of the
    try/counter/except pattern the resilience call sites share."""
    try:
        (registry if registry is not None else REGISTRY).counter(
            name, help=help, labels=labels).inc()
    except Exception:
        pass
