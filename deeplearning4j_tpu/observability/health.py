"""Training-health monitor: detect diverging runs and ACT on them.

The reference ships the measurement half of this loop —
BaseStatsListener streams scores/gradient magnitudes so an operator
can *see* a NaN loss or an exploding gradient; large-scale systems
built on the same pattern close the loop automatically (TensorFlow's
health-check / NaN-propagation machinery, arXiv:1605.08695). On TPU
the loop MUST be closed in software: a diverged run silently burns a
pod slice until a human polls a dashboard.

Two detection planes, matched to what each can afford:

1. **Device plane — the fused finite check.** ``fused_health()`` is
   called INSIDE the jitted train step and folds loss, gradients,
   updates and post-update params into ONE length-5 float32 vector::

       [finite_bits, loss, grad_norm, update_norm, param_norm]

   XLA fuses the reductions into the step program, so the marginal
   cost is a handful of fused reduces and exactly ONE extra
   device→host transfer per step (the monitor fetches the vector; it
   never walks leaves with ``block_until_ready``). ``finite_bits`` is
   a bitmask (BIT_LOSS | BIT_GRADS | BIT_UPDATES | BIT_PARAMS), so a
   trip tells you *which* stage went non-finite within one step.
   Under k-step fused training (``fit(steps_per_device_call=k)``,
   models/kstep.py) the executor fetches the stacked ``[k, 5]``
   health block once per device call and hands this listener one row
   per step — EVERY step is still inspected and a trip fires at the
   exact poisoned sub-step; only the device→host cadence changes
   (one fetch per k steps), so detection/rollback lag is bounded by
   k, never lost to fusion.

2. **Host plane — sliding-window detectors** over the scalar stream
   and the existing ``StatsReport`` pipe (chain the monitor as a
   stats storage: ``StatsListener(storage=HealthMonitor(storage=real))``):
   loss divergence and plateau, gradient-norm explosion / vanish,
   update:param ratio outside the healthy ~1e-3 band
   (TrainModule's chart, now a tripwire), dead-activation fraction.

Each detector resolves to a **policy**: ``warn`` (log + record),
``raise`` (abort with :class:`TrainingDivergedError`), or
``rollback`` (raise a rollback-flagged error that
``train/fault_tolerance.ElasticTrainer`` catches to restore the last
good checkpoint — optionally dropping the LR — and continue).
"""

from __future__ import annotations

import collections
import logging
import time
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["TrainingDivergedError", "HealthMonitor", "fused_health",
           "BIT_LOSS", "BIT_GRADS", "BIT_UPDATES", "BIT_PARAMS"]

# fused_health vector layout
H_BITS, H_LOSS, H_GRAD_NORM, H_UPDATE_NORM, H_PARAM_NORM = range(5)

# finite_bits bitmask: which stage of the step went non-finite
BIT_LOSS, BIT_GRADS, BIT_UPDATES, BIT_PARAMS = 1, 2, 4, 8

_POLICIES = ("warn", "raise", "rollback")


class TrainingDivergedError(RuntimeError):
    """Training health check tripped (NaN/Inf, divergence, gradient
    blow-up...). ``rollback`` marks the error as a rollback request:
    ``ElasticTrainer.fit`` catches those, restores the last good
    checkpoint and continues; without a trainer it propagates."""

    def __init__(self, msg: str, anomaly: Optional[dict] = None,
                 rollback: bool = False):
        super().__init__(msg)
        self.anomaly = anomaly
        self.rollback = rollback


def fused_health(loss, grads, updates, params):
    """Build the device-side health vector INSIDE a jitted step.

    Returns a float32 ``[finite_bits, loss, |grads|, |updates|,
    |params|]`` (global L2 norms). All reductions trace into the step
    program — callers must NOT fetch per-leaf results, only this one
    vector (a single device→host scalar transfer when read).
    """
    import jax
    import jax.numpy as jnp

    def _leaves(tree):
        for leaf in jax.tree_util.tree_leaves(tree):
            a = jnp.asarray(leaf)
            if jnp.issubdtype(a.dtype, jnp.inexact):
                yield a

    def _finite(tree):
        ok = jnp.asarray(True)
        for a in _leaves(tree):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
        return ok

    def _norm(tree):
        total = jnp.zeros((), jnp.float32)
        for a in _leaves(tree):
            total = total + jnp.sum(jnp.square(a.astype(jnp.float32)))
        return jnp.sqrt(total)

    loss = jnp.asarray(loss)
    bits = (jnp.where(jnp.isfinite(loss), 0.0, float(BIT_LOSS))
            + jnp.where(_finite(grads), 0.0, float(BIT_GRADS))
            + jnp.where(_finite(updates), 0.0, float(BIT_UPDATES))
            + jnp.where(_finite(params), 0.0, float(BIT_PARAMS)))
    return jnp.stack([bits, loss.astype(jnp.float32), _norm(grads),
                      _norm(updates), _norm(params)])


def _bit_names(bits: int) -> str:
    parts = [name for bit, name in ((BIT_LOSS, "loss"),
                                    (BIT_GRADS, "gradients"),
                                    (BIT_UPDATES, "updates"),
                                    (BIT_PARAMS, "params"))
             if bits & bit]
    return "+".join(parts) or "?"


class HealthMonitor(TrainingListener):
    """Training listener that watches, then acts.

    Attach with ``model.add_listeners(HealthMonitor(...))``; the
    executors see ``wants_device_health`` and compile the fused
    finite check into the train step. Optionally chain it into the
    stats pipe (``storage=`` forwards every report after inspecting
    it) and hand it a ``recorder`` (FlightRecorder) so every anomaly
    lands in the post-mortem ring.

    ``policy`` is the default for the hard detectors (``non_finite``,
    ``loss_divergence``, ``grad_explosion``); advisory detectors
    (``loss_plateau``, ``grad_vanish``, ``update_ratio``,
    ``dead_activations``) default to ``warn``. Override any of them
    per-detector via ``policies={"loss_plateau": "raise", ...}``.
    """

    # executors check this flag to compile the fused finite check
    # into the jitted train step
    wants_device_health = True

    _ADVISORY = ("loss_plateau", "grad_vanish", "update_ratio",
                 "dead_activations")

    def __init__(self, policy: str = "warn", *,
                 policies: Optional[Dict[str, str]] = None,
                 window: int = 25,
                 divergence_factor: float = 4.0,
                 divergence_patience: int = 3,
                 plateau_window: int = 50, plateau_tol: float = 1e-5,
                 grad_explosion: float = 1e4,
                 grad_spike_factor: float = 100.0,
                 grad_vanish: float = 1e-10, vanish_patience: int = 5,
                 ratio_band=(1e-6, 1e-1), ratio_patience: int = 3,
                 dead_threshold: float = 0.9, dead_eps: float = 1e-7,
                 check_activations_every: int = 0,
                 warn_interval: Optional[int] = None,
                 heal_after: int = 100,
                 storage=None, recorder=None, registry=None,
                 history_limit: int = 256):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}")
        for k, v in (policies or {}).items():
            if v not in _POLICIES:
                raise ValueError(f"policy for {k!r} must be one of "
                                 f"{_POLICIES}, got {v!r}")
        self.policy = policy
        self.policies = dict(policies or {})
        self.window = max(2, window)
        self.divergence_factor = divergence_factor
        self.divergence_patience = max(1, divergence_patience)
        self.plateau_window = max(4, plateau_window)
        self.plateau_tol = plateau_tol
        self.grad_explosion = grad_explosion
        self.grad_spike_factor = grad_spike_factor
        self.grad_vanish = grad_vanish
        self.vanish_patience = max(1, vanish_patience)
        self.ratio_low, self.ratio_high = ratio_band
        self.ratio_patience = max(1, ratio_patience)
        self.dead_threshold = dead_threshold
        self.dead_eps = dead_eps
        self.check_activations_every = check_activations_every
        self.warn_interval = (self.window if warn_interval is None
                              else max(1, warn_interval))
        # a trip/anomaly stops coloring status() after this many
        # healthy iterations — a run that ElasticTrainer rolled back
        # and healed must not stay "diverged" on the dashboard
        self.heal_after = max(1, heal_after)
        self.storage = storage
        self.recorder = recorder
        if registry is None:
            from deeplearning4j_tpu.observability.registry import REGISTRY
            registry = REGISTRY
        self.registry = registry
        # -- state --
        self.anomalies = collections.deque(maxlen=history_limit)
        self.last: Dict[str, object] = {}
        self.device_fetches = 0      # one per step with the fused path
        self.tripped = False         # a raise/rollback-level trip fired
        self._tripped_at: Optional[int] = None
        self._last_anomaly_at: Optional[int] = None
        self._losses = collections.deque(
            maxlen=max(self.window, self.plateau_window))
        self._gnorms = collections.deque(maxlen=self.window)
        self._best: Optional[float] = None
        self._div_streak = 0
        self._vanish_streak = 0
        self._ratio_streak = 0
        self._warn_mark: Dict[str, int] = {}

    # ------------------------------------------------------------------
    # policy plumbing
    # ------------------------------------------------------------------
    def _policy_for(self, kind: str) -> str:
        if kind in self.policies:
            return self.policies[kind]
        if kind in self._ADVISORY:
            return "warn"
        return self.policy

    def _trip(self, kind: str, msg: str, iteration: int,
              value=None) -> None:
        pol = self._policy_for(kind)
        if pol == "warn":
            # de-spam: a plateaued loss stays plateaued every step —
            # one warning per warn_interval per detector
            mark = self._warn_mark.get(kind)
            if mark is not None and iteration - mark < self.warn_interval:
                return
            self._warn_mark[kind] = iteration
        anomaly = {"kind": kind, "iteration": int(iteration),
                   "message": msg, "value": value,
                   "policy": pol, "time": time.time()}
        self.anomalies.append(anomaly)
        self._last_anomaly_at = int(iteration)
        try:
            self.registry.counter(
                "training_anomalies_total",
                help="health-monitor anomalies by detector",
                labels={"type": kind}).inc()
        except Exception:
            pass
        if self.recorder is not None:
            try:
                self.recorder.on_anomaly(anomaly)
            except Exception:
                logger.exception("flight recorder rejected anomaly")
        if pol == "warn":
            logger.warning("health: %s", msg)
            return
        self.tripped = True
        self._tripped_at = int(iteration)
        raise TrainingDivergedError(msg, anomaly=anomaly,
                                    rollback=(pol == "rollback"))

    # ------------------------------------------------------------------
    # per-step path (listener chain)
    # ------------------------------------------------------------------
    def iteration_done(self, model, iteration, score, batch_size):
        vec = getattr(model, "_last_health", None)
        if vec is not None:
            # THE one extra device→host transfer for this step: the
            # whole fused vector in a single fetch. No per-leaf sync.
            arr = np.asarray(vec)
            self.device_fetches += 1
            bits = int(arr[H_BITS])
            loss = float(arr[H_LOSS])
            gnorm = float(arr[H_GRAD_NORM])
            unorm = float(arr[H_UPDATE_NORM])
            pnorm = float(arr[H_PARAM_NORM])
        else:
            # non-fused path (tBPTT chunks, foreign executors): the
            # score scalar is all we can check without extra syncs
            loss = float(score)
            bits = 0 if np.isfinite(loss) else BIT_LOSS
            gnorm = unorm = pnorm = None
        self.last = {"iteration": int(iteration), "loss": loss,
                     "finite_bits": bits, "grad_norm": gnorm,
                     "update_norm": unorm, "param_norm": pnorm,
                     "time": time.time()}
        if bits:
            self._trip(
                "non_finite",
                f"non-finite {_bit_names(bits)} at iteration "
                f"{iteration} (bits={bits})", iteration, value=bits)
            return    # windows would only accumulate garbage
        # heal: after a rollback the run may be healthy again — a
        # trip stops coloring status() once enough clean steps pass
        if self.tripped and self._tripped_at is not None \
                and iteration - self._tripped_at >= self.heal_after:
            self.tripped = False
        self._observe_loss(loss, iteration)
        if gnorm is not None:
            self._observe_grad_norm(gnorm, iteration)
        if (self.check_activations_every
                and iteration % self.check_activations_every == 0):
            self._check_dead_activations(model, iteration)

    def _observe_loss(self, loss: float, iteration: int) -> None:
        self._losses.append(loss)
        if self._best is None or loss < self._best:
            self._best = loss
        # divergence: loss rose far above the best seen, sustained
        threshold = self._best + self.divergence_factor * max(
            abs(self._best), 1.0)
        if len(self._losses) >= self.divergence_patience \
                and loss > threshold:
            self._div_streak += 1
            if self._div_streak >= self.divergence_patience:
                self._div_streak = 0
                self._trip(
                    "loss_divergence",
                    f"loss diverged: {loss:.6g} at iteration "
                    f"{iteration} vs best {self._best:.6g} "
                    f"(> best + {self.divergence_factor:g}x)",
                    iteration, value=loss)
                return
        else:
            self._div_streak = 0
        # plateau: no movement across the plateau window
        if len(self._losses) >= self.plateau_window:
            tail = list(self._losses)[-self.plateau_window:]
            span = max(tail) - min(tail)
            scale = max(abs(sum(tail) / len(tail)), 1e-12)
            if span / scale < self.plateau_tol:
                self._trip(
                    "loss_plateau",
                    f"loss plateaued: relative span "
                    f"{span / scale:.3g} over last "
                    f"{self.plateau_window} steps at iteration "
                    f"{iteration}", iteration, value=span / scale)

    def _observe_grad_norm(self, gnorm: float, iteration: int) -> None:
        spike = None
        if len(self._gnorms) >= self.window // 2:
            med = float(np.median(self._gnorms))
            if med > 0 and gnorm > self.grad_spike_factor * med:
                spike = med
        self._gnorms.append(gnorm)
        if gnorm > self.grad_explosion or spike is not None:
            self._trip(
                "grad_explosion",
                f"gradient norm exploded: {gnorm:.6g} at iteration "
                f"{iteration}"
                + (f" ({self.grad_spike_factor:g}x the window median "
                   f"{spike:.3g})" if spike is not None else
                   f" (> {self.grad_explosion:g})"),
                iteration, value=gnorm)
            return
        if gnorm < self.grad_vanish:
            self._vanish_streak += 1
            if self._vanish_streak >= self.vanish_patience:
                self._vanish_streak = 0
                self._trip(
                    "grad_vanish",
                    f"gradient norm vanished: {gnorm:.3g} for "
                    f"{self.vanish_patience} consecutive steps at "
                    f"iteration {iteration}", iteration, value=gnorm)
        else:
            self._vanish_streak = 0

    def _check_dead_activations(self, model, iteration: int) -> None:
        """Fraction of units whose mean |activation| over the last
        batch is ~0, per layer (the dead-ReLU detector). Costs one
        extra forward pass + host fetch — that's why it's off by
        default and rate-limited by ``check_activations_every``."""
        batch = getattr(model, "_last_batch", None)
        if batch is None or not hasattr(model, "feed_forward"):
            return
        feats = batch[0] if isinstance(batch, tuple) else None
        if feats is None or not hasattr(feats, "shape"):
            return
        try:
            acts = model.feed_forward(feats)
        except Exception:
            return
        if not acts:
            return
        # skip the output layer: a softmax/identity head is never
        # "dead" in the ReLU sense
        inspect = acts[:-1] if len(acts) > 1 else acts
        dead = {}
        for i, a in enumerate(inspect):
            arr = np.asarray(a)
            flat = arr.reshape(arr.shape[0], -1)
            per_unit = np.mean(np.abs(flat), axis=0)
            dead[str(i)] = float(np.mean(per_unit < self.dead_eps))
        self.last["dead_fraction"] = dead
        worst_layer = max(dead, key=dead.get)
        worst = dead[worst_layer]
        if worst > self.dead_threshold:
            self._trip(
                "dead_activations",
                f"layer {worst_layer}: {worst:.0%} of units dead "
                f"(mean |act| < {self.dead_eps:g}) at iteration "
                f"{iteration}", iteration, value=worst)

    # ------------------------------------------------------------------
    # stats-pipe path (chainable storage)
    # ------------------------------------------------------------------
    def put_update(self, report) -> None:
        """Storage-protocol sink: inspect a StatsReport, stamp it with
        the latest device health, forward to the wrapped storage.
        Chain as ``StatsListener(storage=HealthMonitor(storage=real))``.
        """
        try:
            self._observe_report(report)
        finally:
            if self.storage is not None:
                self.storage.put_update(report)

    def _observe_report(self, report) -> None:
        # stamp the report with device-plane numbers so the health
        # fields ride the existing storage/remote-POST pipe
        if self.last:
            if getattr(report, "gradient_norm", None) is None:
                report.gradient_norm = self.last.get("grad_norm")
            if getattr(report, "update_norm", None) is None:
                report.update_norm = self.last.get("update_norm")
            if getattr(report, "param_norm", None) is None:
                report.param_norm = self.last.get("param_norm")
            health = dict(getattr(report, "health", None) or {})
            health.setdefault("finite_bits",
                              self.last.get("finite_bits", 0))
            dead = self.last.get("dead_fraction")
            if dead:
                health.setdefault("worst_dead_fraction",
                                  max(dead.values()))
            report.health = health
        ratios = getattr(report, "update_ratios", None) or {}
        out_of_band = {
            layer: r for layer, r in ratios.items()
            if r > 0 and not (self.ratio_low <= r <= self.ratio_high)}
        if out_of_band:
            self._ratio_streak += 1
            if self._ratio_streak >= self.ratio_patience:
                self._ratio_streak = 0
                worst = max(out_of_band.items(),
                            key=lambda kv: abs(np.log10(kv[1]) + 3))
                self._trip(
                    "update_ratio",
                    f"update:param ratio out of healthy band "
                    f"[{self.ratio_low:g}, {self.ratio_high:g}] for "
                    f"{self.ratio_patience} reports — layer "
                    f"{worst[0]}: {worst[1]:.3g} at iteration "
                    f"{report.iteration}", report.iteration,
                    value=worst[1])
        else:
            self._ratio_streak = 0

    # ------------------------------------------------------------------
    # introspection (the UI /api/health payload)
    # ------------------------------------------------------------------
    def status(self) -> dict:
        last_seen = int(self.last.get("iteration", 0) or 0)
        recent = (self._last_anomaly_at is not None
                  and last_seen - self._last_anomaly_at
                  < self.heal_after)
        if self.tripped:
            status = "diverged"
        elif self.anomalies and recent:
            status = "warning"
        else:
            status = "ok"     # history retained, incident healed
        return {"status": status,
                "policy": self.policy,
                "anomalies": list(self.anomalies)[-20:],
                "anomaly_count": len(self.anomalies),
                "last": dict(self.last),
                "device_fetches": self.device_fetches}
