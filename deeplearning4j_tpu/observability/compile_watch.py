"""Recompile watchdog: compile logging, cache accounting, trip-wire.

The round-5 verdict's unverifiable failure was a *suspected* XLA
compile-cache miss (a 441 s headline leg ≈ warm estimate + cold
compile) that nothing could confirm — compiles were invisible. This
module makes them visible two ways:

1. ``watch(fn)`` wraps a jitted callable. Every call samples the
   executable cache size (``fn._cache_size()``) before/after: a delta
   is a compile — logged with the call's arg shapes and elapsed time,
   counted as a miss (vs a hit). A configurable **trip-wire** fires on
   recompile storms: N compiles of the SAME function within a window,
   the shape-churn bug class (a new batch shape every step silently
   recompiling forever).

2. ``install_global_watch()`` hooks ``jax.monitoring`` so every
   backend compile in the process — watched or not — is counted, with
   persistent-compilation-cache hits/misses split out. bench.py's leg
   subprocesses read this to record ``compile_cache_hit`` per leg.

Both report through the unified metrics registry and (optionally)
drop ``xla_compile`` instants on the tracer so compiles show up in
the Perfetto timeline.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["RecompileStormError", "SteadyStateCompileError",
           "CompileEvent", "CompileWatcher", "watch",
           "install_global_watch", "GlobalCompileStats"]


class RecompileStormError(RuntimeError):
    """Raised when a watched function recompiles ``storm_threshold``
    times inside ``storm_window_s`` seconds — almost always shape
    churn: un-bucketed batch sizes, python scalars promoted to fresh
    weak types, or a config rebuilt per step."""

    def __init__(self, msg: str, events: List["CompileEvent"]):
        super().__init__(msg)
        self.events = events


class SteadyStateCompileError(RuntimeError):
    """Raised by :meth:`GlobalCompileStats.zero_compile_scope` when a
    scope that promised zero compiles (the post-AOT-warmup steady
    state) compiled anyway — a shape escaped the warmup set, or a
    program was invalidated after warming (listener/health toggle,
    optimizer rebuild)."""

    def __init__(self, msg: str, stats: dict):
        super().__init__(msg)
        self.stats = stats


def _describe(x) -> str:
    shape = getattr(x, "shape", None)
    if shape is None:
        return type(x).__name__
    dtype = getattr(x, "dtype", "?")
    return f"{dtype}{list(shape)}"


def arg_signature(args: tuple, kwargs: dict) -> str:
    """Human-readable shapes/dtypes of a call's arguments (pytrees
    flattened), the thing you need to SEE to spot shape churn."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves((args, kwargs))
    except Exception:
        leaves = list(args) + list(kwargs.values())
    parts = [_describe(l) for l in leaves[:16]]
    if len(leaves) > 16:
        parts.append(f"...+{len(leaves) - 16}")
    return "(" + ", ".join(parts) + ")"


@dataclass
class CompileEvent:
    name: str
    signature: str
    elapsed_s: float
    t: float = field(default_factory=time.monotonic)


class _WatchedFunction:
    """Callable proxy sampling the jit executable-cache size around
    each call."""

    def __init__(self, fn, name: str, watcher: "CompileWatcher"):
        if not hasattr(fn, "_cache_size"):
            raise TypeError(
                "watch() needs a jitted callable (jax.jit result with "
                f"_cache_size); got {type(fn).__name__}. Wrap the "
                "function with jax.jit first.")
        self.__wrapped__ = fn
        self._name = name
        self._watcher = watcher
        self._storm: Deque[CompileEvent] = collections.deque(maxlen=256)
        self._lock = threading.Lock()
        self.compiles = 0
        self.hits = 0

    def __call__(self, *args, **kwargs):
        fn = self.__wrapped__
        before = fn._cache_size()
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        elapsed = time.perf_counter() - t0
        if fn._cache_size() > before:
            self._on_compile(args, kwargs, elapsed)
        else:
            with self._lock:
                self.hits += 1
            self._watcher._count_hit()
        return out

    def _on_compile(self, args, kwargs, elapsed):
        ev = CompileEvent(self._name, arg_signature(args, kwargs),
                          elapsed)
        with self._lock:
            self.compiles += 1
            self._storm.append(ev)
            w = self._watcher
            recent = [e for e in self._storm
                      if e.t >= ev.t - w.storm_window_s]
        w._count_compile(ev)
        if len(recent) >= w.storm_threshold:
            msg = (f"recompile storm: {self._name!r} compiled "
                   f"{len(recent)} times in the last "
                   f"{w.storm_window_s:.0f}s — shape churn? recent "
                   "signatures:\n  " +
                   "\n  ".join(f"{e.signature} ({e.elapsed_s:.3f}s)"
                               for e in recent[-8:]))
            if w.on_storm == "raise":
                raise RecompileStormError(msg, recent)
            logger.warning(msg)

    def cache_stats(self) -> dict:
        with self._lock:
            return {"name": self._name, "compiles": self.compiles,
                    "cache_hits": self.hits}

    def __getattr__(self, item):
        # lower/trace/clear_cache etc. pass through to the jit object
        return getattr(self.__wrapped__, item)


class CompileWatcher:
    """Factory for watched callables sharing one storm policy +
    registry wiring. The module-level ``watch()`` uses a default
    instance (warn-only, so production training never dies to its own
    telemetry); tests construct a raising one."""

    def __init__(self, registry=None, tracer=None,
                 storm_threshold: int = 8, storm_window_s: float = 30.0,
                 on_storm: str = "warn", log_compiles: bool = True):
        if on_storm not in ("raise", "warn"):
            raise ValueError("on_storm must be 'raise' or 'warn'")
        if registry is None:
            from deeplearning4j_tpu.observability.registry import REGISTRY
            registry = REGISTRY
        self.registry = registry
        self.tracer = tracer
        self.storm_threshold = storm_threshold
        self.storm_window_s = storm_window_s
        self.on_storm = on_storm
        self.log_compiles = log_compiles
        # bounded: under a warn-mode storm (compile-per-step churn)
        # an unbounded log would itself become the leak
        self.log: Deque[CompileEvent] = collections.deque(maxlen=4096)
        self._lock = threading.Lock()
        self._compiles = registry.counter(
            "xla_watched_compiles_total",
            help="compiles observed by compile_watch.watch()")
        self._hits = registry.counter(
            "xla_watched_cache_hits_total",
            help="watched calls served from the jit executable cache")

    def watch(self, fn, name: Optional[str] = None) -> _WatchedFunction:
        if name is None:
            name = getattr(fn, "__name__", None) or repr(fn)
        return _WatchedFunction(fn, name, self)

    def _count_compile(self, ev: CompileEvent) -> None:
        self._compiles.inc()
        with self._lock:
            self.log.append(ev)
        if self.log_compiles:
            logger.info("XLA compile: %s args=%s (%.3fs)", ev.name,
                        ev.signature, ev.elapsed_s)
        if self.tracer is not None:
            self.tracer.instant("xla_compile",
                                {"fn": ev.name,
                                 "signature": ev.signature,
                                 "elapsed_s": round(ev.elapsed_s, 4)})

    def _count_hit(self) -> None:
        self._hits.inc()


_DEFAULT_WATCHER: Optional[CompileWatcher] = None
_DEFAULT_LOCK = threading.Lock()


def _default_watcher() -> CompileWatcher:
    global _DEFAULT_WATCHER
    with _DEFAULT_LOCK:
        if _DEFAULT_WATCHER is None:
            from deeplearning4j_tpu.observability.tracing import trace
            _DEFAULT_WATCHER = CompileWatcher(tracer=trace)
        return _DEFAULT_WATCHER


def watch(fn, name: Optional[str] = None) -> _WatchedFunction:
    """Wrap a jitted callable with the default (warn-on-storm)
    watcher: per-call hit/miss accounting, compile logging with arg
    shapes, storm warnings."""
    return _default_watcher().watch(fn, name)


# ---------------------------------------------------------------------------
# process-wide compile accounting via jax.monitoring
# ---------------------------------------------------------------------------

class GlobalCompileStats:
    """Totals fed by jax.monitoring events:

    - ``backend_compiles`` / ``compile_secs``: actual XLA backend
      compiles (a persistent-cache hit does NOT fire this).
    - ``cache_requests``: compile requests eligible for the
      persistent compilation cache.
    - ``persistent_cache_hits``: requests served from it.

    ``cache_hit`` is the per-leg question bench asks: did this
    process reuse compiled artifacts instead of cold-compiling?
    """

    def __init__(self, registry=None, tracer=None):
        if registry is None:
            from deeplearning4j_tpu.observability.registry import REGISTRY
            registry = REGISTRY
        self._lock = threading.Lock()
        self.backend_compiles = 0
        self.compile_secs = 0.0
        self.cache_requests = 0
        self.persistent_cache_hits = 0
        self.tracer = tracer
        self._c_compiles = registry.counter(
            "xla_backend_compiles_total",
            help="XLA backend compiles in this process")
        self._c_secs = registry.counter(
            "xla_backend_compile_seconds_total",
            help="wall seconds spent in XLA backend compiles")
        self._c_hits = registry.counter(
            "xla_persistent_cache_hits_total",
            help="compiles served from the persistent XLA cache")

    def mark(self) -> dict:
        """Snapshot for delta accounting (per bench leg section)."""
        with self._lock:
            return {"backend_compiles": self.backend_compiles,
                    "compile_secs": self.compile_secs,
                    "cache_requests": self.cache_requests,
                    "persistent_cache_hits": self.persistent_cache_hits}

    def summary(self, since: Optional[dict] = None) -> dict:
        cur = self.mark()
        if since:
            cur = {k: (round(cur[k] - since[k], 3)
                       if isinstance(cur[k], float)
                       else cur[k] - since[k]) for k in cur}
        else:
            cur["compile_secs"] = round(cur["compile_secs"], 3)
        cur["cache_hit"] = self._cache_hit(cur)
        return cur

    @staticmethod
    def _cache_hit(s: dict) -> Optional[bool]:
        """True = every compile request was served from cache (zero
        cold backend compiles); None when nothing compiled at all (no
        evidence either way)."""
        if s["backend_compiles"] == 0 and s["cache_requests"] == 0:
            return None
        return s["backend_compiles"] == 0

    @property
    def cache_hit(self) -> Optional[bool]:
        return self._cache_hit(self.mark())

    @contextlib.contextmanager
    def zero_compile_scope(self, what: str = "steady state"):
        """Assert that NOTHING in the scope triggers an XLA backend
        compile — the post-AOT-warmup contract: after
        ``model.warmup()`` / ``ModelServer.warmup()`` pre-built every
        expected program, the fit loop or a serving request burst
        must run entirely on compiled executables. Raises
        :class:`SteadyStateCompileError` with the compile deltas
        otherwise."""
        mark = self.mark()
        yield self
        s = self.summary(mark)
        if s["backend_compiles"]:
            raise SteadyStateCompileError(
                f"{what}: {s['backend_compiles']} XLA backend "
                f"compile(s) ({s['compile_secs']:.2f}s) inside a "
                "scope that promised zero after AOT warmup — a shape "
                "escaped the warmup set or a warmed program was "
                "invalidated", s)

    # ---- listeners ----
    def _on_event(self, event: str, **kw) -> None:
        if event == "/jax/compilation_cache/cache_hits":
            with self._lock:
                self.persistent_cache_hits += 1
            self._c_hits.inc()
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            with self._lock:
                self.cache_requests += 1

    def _on_duration(self, event: str, duration: float, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            with self._lock:
                self.backend_compiles += 1
                self.compile_secs += duration
            self._c_compiles.inc()
            self._c_secs.inc(duration)
            if self.tracer is not None:
                self.tracer.instant(
                    "xla_backend_compile",
                    {"elapsed_s": round(duration, 4)})


_GLOBAL_STATS: Optional[GlobalCompileStats] = None


def install_global_watch(registry=None) -> GlobalCompileStats:
    """Idempotently hook jax.monitoring and return the process-wide
    compile stats. jax's listener list has no per-listener removal, so
    this installs exactly once per process."""
    global _GLOBAL_STATS
    with _DEFAULT_LOCK:
        if _GLOBAL_STATS is None:
            from deeplearning4j_tpu.observability.tracing import trace
            stats = GlobalCompileStats(registry=registry, tracer=trace)
            import jax.monitoring as monitoring
            monitoring.register_event_listener(stats._on_event)
            monitoring.register_event_duration_secs_listener(
                stats._on_duration)
            _GLOBAL_STATS = stats
        return _GLOBAL_STATS
