"""Declarative threshold alerting over the metrics registry.

PR 2 gave every subsystem one metrics pipe (``MetricsRegistry``); this
module watches that pipe. An :class:`AlertRule` names any registered
metric — counter value, gauge value, or a histogram quantile — and a
condition; :class:`AlertManager` evaluates the rules (on demand, or on
a background interval) with Prometheus-style semantics:

- **for-duration**: the condition must hold continuously for
  ``for_seconds`` before the alert fires (a one-scrape p99 blip does
  not page);
- **debounce**: after an alert resolves, it cannot re-fire for
  ``debounce_seconds`` (a metric oscillating around the threshold
  fires once per incident, not once per evaluation);
- firing/resolution goes to the log and a pluggable callback, and is
  counted on the registry (``alerts_fired_total``), so alerts are
  themselves observable.

Consumers: ``ModelServer /healthz`` reports ``degraded`` plus the
firing rules instead of an unconditional ``ok``; the training UI's
``/api/health`` panel lists them; operators embed the manager
anywhere a ``MetricsRegistry`` exists.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.observability.registry import (
    Counter, Gauge, Histogram, MetricsRegistry,
)

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["AlertRule", "AlertManager"]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
    "!=": lambda v, t: v != t,
}


@dataclasses.dataclass
class AlertRule:
    """``value(metric{labels}) <op> threshold`` sustained for
    ``for_seconds``. For histograms, ``quantile`` selects the value
    (default p99 — "serving p99 over 250 ms" is one rule)."""

    name: str
    metric: str
    threshold: float
    op: str = ">"
    labels: Optional[Dict[str, str]] = None
    quantile: Optional[float] = None
    for_seconds: float = 0.0
    debounce_seconds: float = 0.0
    severity: str = "warning"
    description: str = ""

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(
                f"op must be one of {sorted(_OPS)}, got {self.op!r}")
        if self.quantile is not None \
                and not 0.0 < self.quantile < 1.0:
            raise ValueError("quantile must be in (0, 1)")


class _RuleState:
    __slots__ = ("pending_since", "firing", "fired_at", "resolved_at",
                 "value")

    def __init__(self):
        self.pending_since: Optional[float] = None
        self.firing = False
        self.fired_at: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.value: Optional[float] = None


class AlertManager:
    """Evaluate alert rules against one registry.

    ``evaluate()`` is cheap and safe to call from a request handler
    (that is exactly what ``/healthz`` does); ``start(interval)``
    runs it on a daemon thread for push-style ``on_fire`` callbacks.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, registry: MetricsRegistry,
                 rules: Optional[List[AlertRule]] = None,
                 on_fire: Optional[Callable[[dict], None]] = None,
                 on_resolve: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry
        self.on_fire = on_fire
        self.on_resolve = on_resolve
        self.clock = clock
        self._lock = threading.Lock()
        # serializes whole evaluation passes: /healthz handlers, the
        # UI and the background thread may all call evaluate()
        # concurrently, and the fire/resolve state machine must step
        # once per crossing, not once per caller. Separate from
        # self._lock so an on_fire callback may call firing().
        self._eval_lock = threading.Lock()
        self._rules: Dict[str, AlertRule] = {}
        self._state: Dict[str, _RuleState] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._fired_counter = registry.counter(
            "alerts_fired_total", help="alert rule firings")
        registry.gauge("alerts_firing",
                       help="currently-firing alert rules",
                       fn=lambda: float(len(self.firing())))
        for r in rules or []:
            self.add_rule(r)

    def add_rule(self, rule: AlertRule) -> AlertRule:
        with self._lock:
            self._rules[rule.name] = rule
            self._state[rule.name] = _RuleState()
        return rule

    def remove_rule(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)
            self._state.pop(name, None)

    # ------------------------------------------------------------------
    def _value(self, rule: AlertRule) -> Optional[float]:
        m = self.registry.get(rule.metric, rule.labels)
        if m is None:
            return None
        try:
            if isinstance(m, Histogram):
                return float(m.quantile(rule.quantile or 0.99))
            if isinstance(m, Gauge):
                v = m.value()
                return None if v is None else float(v)
            if isinstance(m, Counter):
                return float(m.value)
        except Exception:
            logger.exception("alert rule %r: reading %r failed",
                             rule.name, rule.metric)
        return None

    def evaluate(self) -> List[dict]:
        """One evaluation pass; returns the state CHANGES as
        ``{"event": "fire"|"resolve", ...alert}`` dicts."""
        with self._eval_lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> List[dict]:
        now = self.clock()
        changes: List[dict] = []
        with self._lock:
            rules = list(self._rules.values())
        for rule in rules:
            st = self._state.get(rule.name)
            if st is None:
                continue
            v = self._value(rule)
            st.value = v
            cond = (v is not None
                    and _OPS[rule.op](v, rule.threshold))
            if cond:
                if st.firing:
                    continue
                if st.resolved_at is not None and \
                        now - st.resolved_at < rule.debounce_seconds:
                    continue              # debounced
                if st.pending_since is None:
                    st.pending_since = now
                if now - st.pending_since >= rule.for_seconds:
                    st.firing = True
                    st.fired_at = now
                    st.pending_since = None
                    self._fired_counter.inc()
                    alert = self._alert_dict(rule, st)
                    alert["event"] = "fire"
                    changes.append(alert)
                    logger.warning(
                        "ALERT firing: %s — %s{%s} = %s %s %g%s",
                        rule.name, rule.metric, rule.labels or "",
                        v, rule.op, rule.threshold,
                        f" ({rule.description})" if rule.description
                        else "")
                    if self.on_fire is not None:
                        try:
                            self.on_fire(alert)
                        except Exception:
                            logger.exception("on_fire callback failed")
            else:
                st.pending_since = None
                if st.firing:
                    st.firing = False
                    st.resolved_at = now
                    alert = self._alert_dict(rule, st)
                    alert["event"] = "resolve"
                    changes.append(alert)
                    logger.warning("ALERT resolved: %s", rule.name)
                    if self.on_resolve is not None:
                        try:
                            self.on_resolve(alert)
                        except Exception:
                            logger.exception(
                                "on_resolve callback failed")
        return changes

    def _alert_dict(self, rule: AlertRule, st: _RuleState) -> dict:
        return {"name": rule.name, "metric": rule.metric,
                "labels": rule.labels, "op": rule.op,
                "threshold": rule.threshold,
                "quantile": rule.quantile, "value": st.value,
                "severity": rule.severity,
                "description": rule.description,
                "fired_at": st.fired_at}

    def firing(self) -> List[dict]:
        """Currently-firing alerts (does NOT evaluate — pair with
        ``evaluate()`` or a running background thread)."""
        with self._lock:
            return [self._alert_dict(self._rules[n], st)
                    for n, st in self._state.items()
                    if st.firing and n in self._rules]

    # ------------------------------------------------------------------
    def start(self, interval_s: float = 5.0) -> "AlertManager":
        # each loop generation gets its OWN stop event, captured by
        # the closure: a shared event that start() clears could be
        # cleared before the previous (stopping) loop has observed
        # it, orphaning that loop with no handle
        stop = threading.Event()

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:
                    logger.exception("alert evaluation failed")

        # check-then-spawn under the lock: two racing start() calls
        # must not each launch an evaluation loop (every on_fire
        # callback would fire twice) — found by graftlint GL004
        with self._lock:
            if self._thread is not None:
                return self
            self._stop = stop
            self._thread = threading.Thread(target=loop, daemon=True,
                                            name="alert-manager")
            self._thread.start()
        return self

    def stop(self) -> None:
        # the flag must flip under the SAME lock as the thread swap:
        # set outside, a racing start() could swap in a fresh event
        # between our set and our swap
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:                   # join OUTSIDE the lock:
            t.join(timeout=5.0)             # the loop's evaluate()
        #                                     briefly takes _lock
