"""Command-line surface.

Mirrors the reference's thin JCommander CLIs (SURVEY §1 'CLI surface'):
ParallelWrapperMain (--modelPath --workers --prefetchSize ...),
PlayUIServer main, NearestNeighborsServer main. One entry point with
subcommands:

    python -m deeplearning4j_tpu train --model m.zip --data d.csv \
        --features 4 --label-index 4 --classes 3 --workers 8
    python -m deeplearning4j_tpu ui --port 9000
    python -m deeplearning4j_tpu serve --model m.zip --port 8080
    python -m deeplearning4j_tpu serve-knn --points p.npy --port 9200
    python -m deeplearning4j_tpu summary --model m.zip
"""

from __future__ import annotations

import argparse
import os
import sys

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()     # a dead TPU tunnel must not hang CPU-pinned CLIs


def _cmd_train(args):
    if args.chaos:
        # fault injection for the bench/soak path: the plan is JSON
        # (inline or a file); the effective seed is printed so any
        # chaotic run can be replayed exactly
        from deeplearning4j_tpu import chaos
        inj = chaos.install(args.chaos, seed=args.chaos_seed)
        print(f"chaos: fault plan installed "
              f"({len(inj.plan.faults)} spec(s), seed {inj.seed}; "
              f"replay with --chaos-seed {inj.seed})")
    from deeplearning4j_tpu.data.records import (CSVRecordReader,
                                                 RecordReaderDataSetIterator)
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
    from deeplearning4j_tpu.train.listeners import (PerformanceListener,
                                                    ScoreIterationListener)
    from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                          write_model)
    if args.health == "rollback" and args.workers and args.workers > 1:
        # nothing in the ParallelWrapper path catches the rollback
        # flag — failing loudly beats silently losing the policy
        sys.exit("train: --health rollback is not supported with "
                 "--workers >1 (rollback needs the single-worker "
                 "ElasticTrainer loop); use --health warn/raise or "
                 "drop --workers")
    if args.k_step < 1:
        sys.exit("train: --k-step must be >= 1")
    if args.mesh and args.workers and args.workers > 1:
        # two ways to state the same parallelism — refuse the
        # ambiguity (--mesh "dp=N" is the --workers N successor)
        sys.exit("train: pass either --mesh (declarative sharded "
                 "fit) or --workers (legacy data-parallel wrapper), "
                 "not both")
    if args.k_step > 1 and args.workers and args.workers > 1:
        # the wrapper's per-batch path has no fused program on this
        # CLI route; the declarative spec composes with fusion
        sys.exit("train: --k-step >1 is not supported with "
                 "--workers >1 (the legacy wrapper steps per-batch); "
                 "use --mesh \"dp=N\" — the sharded fit path fuses "
                 "k-step windows")
    if args.aot_warmup and args.workers and args.workers > 1:
        # warmup() compiles the SINGLE-worker train programs; the
        # ParallelWrapper path dispatches a different (mesh) program,
        # so the flag would burn startup time on dead executables and
        # still compile cold at the first mesh step
        sys.exit("train: --aot-warmup is not supported with "
                 "--workers >1 (warmup builds the single-worker "
                 "programs; the mesh step compiles its own — with "
                 "--mesh the warmed programs ARE the sharded ones)")
    model = restore_model(args.model)
    if args.mesh:
        # install the mesh BEFORE warmup/elastic construction: the
        # warmed programs and any checkpoint restore must be the
        # sharded, output-pinned ones
        model.use_mesh(args.mesh)
        print(f"mesh: {model._mesh_ctx.plan} over "
              f"{model._mesh_ctx.plan.n_devices()} device(s)")
    rr = CSVRecordReader().initialize(args.data)
    it = RecordReaderDataSetIterator(
        rr, args.batch_size, label_index=args.label_index,
        num_classes=args.classes, regression=args.classes == 0)
    model.set_listeners(ScoreIterationListener(10),
                        PerformanceListener(frequency=10))
    if args.health:
        from deeplearning4j_tpu.observability.flight_recorder import (
            get_recorder)
        from deeplearning4j_tpu.observability.health import (
            HealthMonitor)
        model.add_listeners(HealthMonitor(policy=args.health,
                                          recorder=get_recorder()))
    if args.aot_warmup:
        # AOT warmup AFTER listeners are attached (the health toggle
        # changes the train-step program signature): peek one batch
        # for its shape, lower+compile the k-step and k=1 programs,
        # rewind the iterator — steady-state training then never
        # traces or compiles (compile_watch can prove it)
        ds0 = next(iter(it), None)
        if ds0 is None:
            sys.exit("train: --aot-warmup found no data to derive "
                     "the batch shape from")
        it.reset()
        rep = model.warmup(ds0, steps_per_device_call=args.k_step)
        print("aot warmup: "
              + (", ".join(f"{n} compiled in {s:.2f}s"
                           for n, s in rep.items())
                 or "all programs already warm"))
    use_elastic = args.health == "rollback" or args.async_checkpoint
    if args.workers and args.workers > 1:
        # under ElasticTrainer the trainer owns the batch loop and
        # drives wrapper.fit_batch — wrapper-level prefetch never
        # runs there, so build it prefetch-free and say so rather
        # than silently ignoring the flag
        wrapper_prefetch = 0 if use_elastic else args.prefetch
        if use_elastic and args.prefetch:
            print("train: --prefetch is inactive under the elastic "
                  "trainer (it owns the batch loop; checkpointable "
                  "iterator state requires consuming batches in "
                  "step order)")
        pw = (ParallelWrapper.builder(model).workers(args.workers)
              .prefetch_buffer(wrapper_prefetch).build())
        if use_elastic:
            # data-parallel AND preemption-tolerant: the trainer
            # checkpoints (off-thread with --async-checkpoint) while
            # the wrapper runs the mesh step
            from deeplearning4j_tpu.train.fault_tolerance import (
                ElasticTrainer)
            ckpt_dir = (args.output or args.model) + ".ckpts"
            ElasticTrainer(model, ckpt_dir, save_every=10,
                           async_checkpoint=args.async_checkpoint,
                           wrapper=pw).fit(it, epochs=args.epochs)
        else:
            pw.fit(it, epochs=args.epochs)
    elif use_elastic:
        # the rollback policy needs a checkpoint loop to roll back TO
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        ckpt_dir = (args.output or args.model) + ".ckpts"
        ElasticTrainer(model, ckpt_dir, save_every=10,
                       async_checkpoint=args.async_checkpoint,
                       steps_per_device_call=args.k_step).fit(
            it, epochs=args.epochs)
    else:
        model.fit(it, epochs=args.epochs,
                  steps_per_device_call=args.k_step)
    out = args.output or args.model
    write_model(model, out)
    print(f"trained {args.epochs} epochs; saved to {out}")


def _install_chaos(args):
    if not args.chaos:
        return
    from deeplearning4j_tpu import chaos
    inj = chaos.install(args.chaos, seed=args.chaos_seed)
    print(f"chaos: fault plan installed "
          f"({len(inj.plan.faults)} spec(s), seed {inj.seed}; "
          f"replay with --chaos-seed {inj.seed})")


def _ps_batches(args):
    from deeplearning4j_tpu.data.records import (
        CSVRecordReader, RecordReaderDataSetIterator)
    rr = CSVRecordReader().initialize(args.data)
    it = RecordReaderDataSetIterator(
        rr, args.batch_size, label_index=args.label_index,
        num_classes=args.classes, regression=args.classes == 0)
    return list(it)


def _cmd_train_ps(args):
    """Async parameter-server training (the reference's Aeron
    ``VoidParameterServer`` sharing, TF-style PS architecture). The
    launcher role runs the server in-process and spawns worker
    subprocesses; the server/worker roles exist so soak tests (and
    real deployments) can place each piece in its own killable
    process."""
    _install_chaos(args)
    from deeplearning4j_tpu.parallel.paramserver import (
        ParameterServer, PSClient, PSWorker)
    from deeplearning4j_tpu.util.model_serializer import (
        restore_model, write_model)
    max_staleness = (None if args.max_staleness < 0
                     else args.max_staleness)

    if args.role == "worker":
        if not args.connect:
            sys.exit("train-ps: --role worker needs --connect "
                     "HOST:PORT")
        host, _, port = args.connect.rpartition(":")
        model = restore_model(args.model)
        if model.params is None:
            model.init()
        batches = _ps_batches(args)
        shard = batches[args.worker_index::max(1, args.num_workers)]
        client = PSClient((host, int(port)),
                          op_timeout_s=args.op_timeout)
        try:
            worker = PSWorker(model, client,
                              threshold=args.push_threshold,
                              name=f"ps-worker-{args.worker_index}")
            stats = worker.run(shard, epochs=args.epochs)
        finally:
            client.close()
        print(f"train-ps worker {args.worker_index}: "
              f"{stats['steps']} steps, "
              f"{stats['pushes_applied']} pushes applied, "
              f"{stats['stale_rejects']} stale rejects, "
              f"last loss {stats['last_loss']:.4f}")
        return

    model = restore_model(args.model)
    if model.params is None:
        model.init()
    ckpt_dir = args.ckpt_dir or ((args.output or args.model)
                                 + ".ps-ckpts")
    server = ParameterServer(
        model.params, lr=args.lr, max_staleness=max_staleness,
        host=args.host, port=args.ps_port, checkpoint_dir=ckpt_dir,
        save_every=args.save_every,
        heartbeat_timeout_s=args.heartbeat_timeout).start()
    print(f"train-ps: parameter server on "
          f"{server.host}:{server.port} (version {server.version}, "
          f"max_staleness={max_staleness}, ckpts in {ckpt_dir})",
          flush=True)

    if args.role == "server":
        # standalone (killable) server: serve until interrupted,
        # then drain — a restart against the same --ckpt-dir resumes
        # from the newest durable generation
        import time
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            server.stop()
            model.params = server.params_tree()
            if args.output:
                write_model(model, args.output)
                print(f"train-ps: saved v{server.version} to "
                      f"{args.output}")
        return

    # launcher: one worker subprocess per --ps-workers. With
    # --net-chaos the workers dial a seeded TCP fault proxy fronting
    # the DPS1 wire instead of the server directly — the corrupt/
    # truncate/partition drill for the parameter-server protocol.
    import subprocess
    net_proxy = None
    connect_to = f"{server.host}:{server.port}"
    if getattr(args, "net_chaos", None):
        from deeplearning4j_tpu.chaos.netproxy import NetChaosProxy
        try:
            net_proxy = NetChaosProxy(
                (server.host, server.port), plan=args.net_chaos,
                seed=args.net_chaos_seed, site="net.ps",
                name="ps").start()
        except (ValueError, TypeError, OSError) as e:
            server.stop()
            raise SystemExit(f"bad --net-chaos plan: {e}")
        connect_to = f"{net_proxy.listen_host}:{net_proxy.port}"
        print(f"net-chaos: PS wire proxied on {connect_to} "
              f"(seed {net_proxy.seed}; replay with "
              f"--net-chaos-seed {net_proxy.seed})", flush=True)
    procs = []
    try:
        for i in range(args.ps_workers):
            cmd = [sys.executable, "-m", "deeplearning4j_tpu",
                   "train-ps", "--role", "worker",
                   "--connect", connect_to,
                   "--model", args.model, "--data", args.data,
                   "--label-index", str(args.label_index),
                   "--classes", str(args.classes),
                   "--batch-size", str(args.batch_size),
                   "--epochs", str(args.epochs),
                   "--worker-index", str(i),
                   "--num-workers", str(args.ps_workers),
                   "--push-threshold", str(args.push_threshold),
                   "--op-timeout", str(args.op_timeout)]
            procs.append(subprocess.Popen(cmd))
        failures = 0
        for i, pr in enumerate(procs):
            if pr.wait() != 0:
                failures += 1
                print(f"train-ps: worker {i} exited "
                      f"{pr.returncode}", file=sys.stderr)
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()
        if net_proxy is not None:
            net_proxy.stop()
        server.stop()
    model.params = server.params_tree()
    out = args.output or args.model
    write_model(model, out)
    st = server.stats
    print(f"train-ps: v{server.version} "
          f"({st['pushes_applied']} pushes applied, "
          f"{st['pushes_stale']} stale, "
          f"{st['workers_reaped']} reaped, "
          f"{st['restarts']} restarts); saved to {out}")
    if failures:
        sys.exit(f"train-ps: {failures} worker(s) failed")


def _cmd_ui(args):
    import time
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.stats import FileStatsStorage
    server = UIServer(port=args.port)
    server.start()
    if args.stats_file:
        server.attach(FileStatsStorage(args.stats_file))
    print(f"UI on http://localhost:{server.port}/ (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


def _cmd_serve_knn(args):
    import time
    import numpy as np
    from deeplearning4j_tpu.services.nearest_neighbors import (
        NearestNeighborsServer)
    pts = np.load(args.points)
    server = NearestNeighborsServer(pts, args.port, args.distance)
    server.start()
    print(f"k-NN server on port {server.port} ({pts.shape[0]} points)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


def _parse_model_spec(spec):
    """[NAME=]PATH: an existing file wins outright — a bare path
    may itself contain '=' (run=3/m.zip); otherwise split on
    the first '=' only when the prefix looks like a name."""
    name, sep, path = spec.partition("=")
    if os.path.exists(spec) or not sep or os.sep in name \
            or "/" in name:
        name, path = "default", spec
    return name, path


def _parse_random_corpus(spec):
    """``random:n=4096,dim=64,seed=0[,clusters=32]`` -> params dict.
    Clustered gaussian data, NOT uniform: uniform low-D gaussians are
    adversarial for IVF (every cell borders every other), clustered
    corpora are what the recall acceptance gate measures."""
    params = {"n": 4096, "dim": 64, "seed": 0, "clusters": 32}
    body = spec.split(":", 1)[1] if ":" in spec else ""
    for part in filter(None, body.split(",")):
        key, sep, val = part.partition("=")
        if not sep or key not in params:
            raise SystemExit(
                f"bad --index random spec field {part!r} (want "
                "n=,dim=,seed=,clusters=)")
        try:
            params[key] = int(val)
        except ValueError:
            raise SystemExit(f"--index random spec field {part!r} "
                             "must be an integer")
    if params["n"] < 1 or params["dim"] < 1 or params["clusters"] < 1:
        raise SystemExit("--index random spec wants positive "
                         "n/dim/clusters")
    return params


def _load_corpus(spec):
    """--index SPEC -> (ids, vectors, vocab|None, table|None).

    SPEC is either ``random:...`` (synthetic clustered corpus with a
    w{i}->row vocab so text search works out of the box) or a .npz
    with ``vectors`` (n,d) [+ ``ids``] [+ ``tokens``/``table`` for
    the embedder].
    """
    import numpy as np
    if spec.startswith("random:") or spec == "random":
        p = _parse_random_corpus(spec)
        rng = np.random.default_rng(p["seed"])
        centers = rng.normal(size=(p["clusters"], p["dim"]))
        assign = rng.integers(0, p["clusters"], size=p["n"])
        vectors = (centers[assign]
                   + 0.15 * rng.normal(size=(p["n"], p["dim"]))
                   ).astype(np.float32)
        ids = np.arange(p["n"], dtype=np.int64)
        vocab = {f"w{i}": i for i in range(p["n"])}
        return ids, vectors, vocab, vectors
    if not os.path.exists(spec):
        raise SystemExit(f"--index: no such corpus file: {spec}")
    data = np.load(spec, allow_pickle=False)
    if "vectors" not in data:
        raise SystemExit(f"--index: {spec} has no 'vectors' array "
                         f"(found {sorted(data.files)})")
    vectors = np.asarray(data["vectors"], np.float32)
    ids = (np.asarray(data["ids"], np.int64) if "ids" in data
           else np.arange(vectors.shape[0], dtype=np.int64))
    vocab = table = None
    if "tokens" in data and "table" in data:
        toks = [str(t) for t in data["tokens"]]
        vocab = {t: i for i, t in enumerate(toks)}
        table = np.asarray(data["table"], np.float32)
    return ids, vectors, vocab, table


def _retrieval_factory(args):
    """--index/--index-kind/--nlist/--nprobe/--index-metric -> a
    ``metrics -> RetrievalService`` factory. Each call builds a FRESH
    index + embedder, so every replica owns its device arrays (and a
    replaced replica reloads, not shares, the corpus)."""
    spec, kind = args.index, args.index_kind
    metric, nlist = args.index_metric, args.nlist
    nprobe = args.nprobe

    def factory(metrics):
        from deeplearning4j_tpu.retrieval import (BruteForceIndex,
                                                  IVFIndex,
                                                  TextEmbedder)
        from deeplearning4j_tpu.serving.retrieval_backend import (
            RetrievalService)
        ids, vectors, vocab, table = _load_corpus(spec)
        dim = int(vectors.shape[1])
        if kind == "ivf":
            index = IVFIndex(dim, nlist=nlist, metric=metric)
            index.build(ids, vectors)
        else:
            index = BruteForceIndex(dim, metric=metric)
            index.add(ids, vectors)
        embedder = None
        if vocab is not None and table is not None:
            embedder = TextEmbedder(vocab, table)
        svc = RetrievalService(
            index, embedder=embedder,
            max_batch_size=args.max_batch_size,
            queue_limit=args.queue_limit, wait_ms=args.wait_ms,
            default_nprobe=nprobe)
        return svc.attach_metrics(metrics)

    return factory


def _add_index_flags(p):
    """The retrieval knobs serve and serve-fleet share."""
    p.add_argument("--index", metavar="SPEC", default=None,
                   help="host a vector index: 'random:n=4096,dim=64,"
                        "seed=0,clusters=32' or an .npz with "
                        "vectors[+ids][+tokens/table for /v1/embed] "
                        "(enables /v1/embed /v1/search /v1/index/*)")
    p.add_argument("--index-kind", choices=("brute", "ivf"),
                   default="brute",
                   help="brute = exact matmul top-k; ivf = coarse-"
                        "quantized cells, recall traded for latency "
                        "via nprobe")
    p.add_argument("--nlist", type=int, default=16,
                   help="IVF cell count (k-means centroids)")
    p.add_argument("--nprobe", type=int, default=None,
                   help="server default IVF cells probed per query "
                        "(requests may override per call)")
    p.add_argument("--index-metric",
                   choices=("cosine", "dot", "euclidean"),
                   default="cosine", help="similarity metric")


def _cmd_serve(args):
    import time
    from deeplearning4j_tpu.serving.http import ModelServer
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.util.model_serializer import restore_model
    if not args.model and not args.index:
        raise SystemExit("serve needs --model and/or --index")
    registry = ModelRegistry()
    for spec in args.model or []:
        name, path = _parse_model_spec(spec)
        version = registry.register(name, restore_model(path))
        print(f"registered {name} v{version} from {path}")
    metrics = ServingMetrics()
    slos = None
    if args.slo:
        # declarative SLO rules (JSON inline or a file); burn rates
        # are evaluated on /healthz and /metrics reads, breaches
        # degrade health and leave flight-recorder bundles carrying
        # the offending trace ids
        from deeplearning4j_tpu.observability.slo import SLOMonitor
        slos = SLOMonitor.from_config(metrics.registry, args.slo)
        print(f"SLOs: {', '.join(s['name'] for s in slos.status())}")
    server = ModelServer(
        registry, port=args.port, host=args.host,
        max_batch_size=args.max_batch_size,
        queue_limit=args.queue_limit, wait_ms=args.wait_ms,
        slots=args.slots, capacity=args.capacity, metrics=metrics,
        sample_rate=args.trace_sample, slow_ms=args.slow_ms,
        slos=slos, kv_mode=args.kv_mode, page_size=args.page_size,
        kv_pages=args.kv_pages, mesh=args.mesh,
        retrieval=_retrieval_factory(args) if args.index else None)
    if args.index:
        st = server.retrieval.stats()["index"]
        print(f"index: {st['kind']}/{st['metric']} — "
              f"{st['vectors']} vector(s), dim {st['dim']}"
              + (f", nlist {st['nlist']}" if "nlist" in st else "")
              + ("; embedder attached (/v1/embed, text /v1/search)"
                 if server.retrieval.embedder is not None else ""))
    if args.mesh:
        print(f"serving mesh: {server.mesh_plan} "
              f"({server.mesh_plan.n_devices()} device(s); predict "
              f"tensor-parallel, generate unsharded-replica only)")
    if args.aot_warmup:
        # pre-compile every hosted model's serving executables (pow2
        # predict buckets + generate prefill/decode) BEFORE the
        # listener takes traffic: the first real request never pays
        # an XLA compile
        rep = server.warmup()
        for name, r in rep.items():
            print(f"aot warmup: {name} v{r['version']} — predict "
                  f"buckets {r['predict_buckets']}, generate="
                  f"{r['generate']} ({r['seconds']:.1f}s"
                  + (f"; skipped: {'; '.join(r['skipped'])}"
                     if r["skipped"] else "") + ")")
    server.start()
    print(f"serving on http://{args.host}:{server.port}/ "
          f"(/v1/predict /v1/generate /v1/models /healthz /metrics "
          f"/debug/requests /debug/slots /debug/traces; trace "
          f"sampling {args.trace_sample:g}; ctrl-c drains and stops)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("draining...")
        server.stop(drain=True)


def _cmd_serve_fleet(args):
    import time
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from deeplearning4j_tpu.util.model_serializer import restore_model
    bounds = None
    if args.autoscale:
        # validate EVERY autoscaler input BEFORE booting anything: a
        # typo'd bound, watermark band, or SLO rule must exit here,
        # not crash after N replicas started (and leak them)
        try:
            lo, _, hi = args.autoscale.partition(":")
            bounds = (int(lo), int(hi))
        except ValueError:
            raise SystemExit(
                f"--autoscale wants MIN:MAX, got {args.autoscale!r}")
        if bounds[0] < 1 or bounds[1] < bounds[0]:
            raise SystemExit(
                f"--autoscale bounds must satisfy 1 <= MIN <= MAX, "
                f"got {args.autoscale!r}")
        if not args.queue_low < args.queue_high:
            raise SystemExit(
                f"--queue-low ({args.queue_low:g}) must sit below "
                f"--queue-high ({args.queue_high:g}) — the band "
                "between them is the anti-flap dead zone")
    if args.slo:
        # --slo stands on its own (burn rates + slo_breach on the
        # router's /metrics, autoscaler or not) and must also fail
        # fast: validate the rules before any replica boots
        from deeplearning4j_tpu.observability.registry import (
            MetricsRegistry)
        from deeplearning4j_tpu.observability.slo import SLOMonitor
        try:
            # throwaway registry: this pass only validates the
            # rules; the real monitor binds to the router's
            # registry once the router exists
            SLOMonitor.from_config(MetricsRegistry(), args.slo)
        except Exception as e:
            raise SystemExit(f"bad --slo rules: {e}")
    if args.chaos:
        from deeplearning4j_tpu import chaos
        inj = chaos.install(args.chaos, seed=args.chaos_seed)
        print(f"chaos: fault plan installed "
              f"({len(inj.plan.faults)} spec(s), seed {inj.seed}; "
              f"replay with --chaos-seed {inj.seed})")
    if args.net_chaos:
        # validate the network plan before any replica boots, like
        # --slo/--autoscale: a typo'd kind must fail HERE
        from deeplearning4j_tpu.chaos.netproxy import parse_net_plan
        try:
            parse_net_plan(args.net_chaos)
        except (ValueError, TypeError, OSError) as e:
            raise SystemExit(f"bad --net-chaos plan: {e}")
    if not args.model and not args.index:
        raise SystemExit("serve-fleet needs --model and/or --index")
    if args.rollout:
        # fail fast like --slo/--autoscale: an unpromotable rollout
        # (no collector = no gate evidence = holds forever) or an
        # unreadable candidate spec must exit before replicas boot
        if args.collector is None:
            raise SystemExit(
                "--rollout needs --collector: the promotion gate "
                "reads the merged replica-labeled series, and "
                "without them the rollout would hold forever")
        if not args.model:
            raise SystemExit(
                "--rollout replaces --model served in-process; "
                "an --index-only fleet has no model versions to "
                "roll")
        if not 0.0 < args.rollout_canary_weight <= 1.0:
            raise SystemExit(
                f"--rollout-canary-weight must be in (0, 1], got "
                f"{args.rollout_canary_weight:g}")
        if not 0.0 <= args.rollout_shadow_sample <= 1.0:
            raise SystemExit(
                f"--rollout-shadow-sample must be in [0, 1], got "
                f"{args.rollout_shadow_sample:g}")
    rollout_specs = [_parse_model_spec(s)
                     for s in args.rollout or []]
    specs = [_parse_model_spec(s) for s in args.model or []]

    def factory(specs=specs):
        # called once per replica boot: each replica owns its model
        # instances (and their compiled executables) outright
        return {name: restore_model(path) for name, path in specs}

    roles = None
    if args.roles:
        from deeplearning4j_tpu.serving.fleet import parse_roles
        try:
            roles = parse_roles(args.roles, args.replicas)
        except ValueError as e:
            raise SystemExit(f"bad --roles: {e}")
    fleet = ReplicaFleet(
        factory, n=args.replicas, roles=roles,
        net_chaos=args.net_chaos or None,
        net_chaos_seed=args.net_chaos_seed,
        server_kwargs=dict(max_batch_size=args.max_batch_size,
                           queue_limit=args.queue_limit,
                           wait_ms=args.wait_ms, slots=args.slots,
                           capacity=args.capacity,
                           kv_mode=args.kv_mode,
                           page_size=args.page_size,
                           kv_pages=args.kv_pages,
                           mesh=args.mesh,
                           retrieval=_retrieval_factory(args)
                           if args.index else None)).start()
    if args.net_chaos:
        print(f"net-chaos: every replica fronted by a seeded TCP "
              f"fault proxy (seed {fleet._net_seed}; replay with "
              f"--net-chaos-seed {fleet._net_seed})")
    if args.index:
        print(f"index: {args.index_kind} over --index {args.index} "
              f"(one copy per replica; /v1/search fails over, "
              f"/v1/index/* fans out to every replica)")
    if roles:
        print("fleet roles: " + ", ".join(
            f"replica {r.id}={r.role}" for r in fleet.snapshot()))
    router = Router(
        fleet, port=args.port, host=args.host,
        probe_interval_s=args.probe_interval,
        hedge_after_s=None if args.hedge_after_ms <= 0
        else args.hedge_after_ms / 1e3,
        kv_routing=not args.no_kv_routing,
        sample_rate=args.trace_sample).start()
    slos = None
    if args.slo:
        from deeplearning4j_tpu.observability.slo import SLOMonitor
        # objectives over the ROUTER's own latency family: the burn
        # rate then measures what CLIENTS experienced through
        # failover/hedging — and the slo_breach/slo_burn_rate
        # gauges live on the router's /metrics whether or not the
        # autoscaler consumes them
        slos = SLOMonitor.from_config(router.registry, args.slo)
        print(f"slo: {len(slos.status())} objective(s) over the "
              "router registry (slo_breach on /metrics)")
    collector = None
    if args.collector is not None:
        from deeplearning4j_tpu.observability.fleetobs import (
            FleetCollector)
        fleet_slos = ()
        if args.slo:
            # the SAME rules, judged a second time over the MERGED
            # series: the router-level monitor above sees one
            # process; the collector's copy sees the whole fleet
            from deeplearning4j_tpu.observability.registry import (
                MetricsRegistry)
            from deeplearning4j_tpu.observability.slo import (
                SLOMonitor)
            fleet_slos = tuple(SLOMonitor.from_config(
                MetricsRegistry(), args.slo)._slos.values())
        collector = FleetCollector(
            fleet=fleet, router=router,
            interval_s=args.collector_interval,
            port=args.collector,
            slos=fleet_slos,
            incident_dir=args.incident_dir).start()
        router.attach_fleet_health(collector.fleet_health)
        print(f"fleet collector on http://127.0.0.1:"
              f"{collector.port}/ scraping every "
              f"{args.collector_interval:g}s (/metrics "
              f"/fleet/snapshot /traces /healthz; incidents under "
              f"{collector.incident_dir})")
    scaler = None
    if bounds is not None:
        from deeplearning4j_tpu.serving.autoscaler import Autoscaler
        lo, hi = bounds
        scaler = Autoscaler(
            fleet, router, slos=slos,
            min_replicas=lo, max_replicas=hi,
            tick_interval_s=args.autoscale_tick,
            queue_high=args.queue_high,
            queue_low=args.queue_low,
            collector=collector).start()
        print(f"autoscaler: bounds {lo}..{hi}, tick "
              f"{args.autoscale_tick:g}s, queue watermarks "
              f"{args.queue_low:g}/{args.queue_high:g}"
              + (f", {len(slos.status())} SLO(s)" if slos else "")
              + (", merged signals via collector"
                 if collector is not None else ""))
    rollout = None
    if args.rollout:
        from deeplearning4j_tpu.serving.rollout import (
            RolloutController)

        def candidate_factory(specs=rollout_specs):
            return {name: restore_model(path)
                    for name, path in specs}

        rollout = RolloutController(
            fleet, router,
            candidate_factory=candidate_factory,
            candidate_version=args.rollout_version,
            collector=collector, autoscaler=scaler,
            canary_weight=args.rollout_canary_weight,
            shadow_sample=args.rollout_shadow_sample,
            min_requests=args.rollout_min_requests)
        router.attach_rollout(rollout)
        print(f"rollout: candidate staged "
              f"({', '.join(n for n, _ in rollout_specs)}) — "
              f"armed, not deploying; trigger with "
              f"'fleet-rollout start --router "
              f"http://{args.host}:{router.port}' (canary weight "
              f"{args.rollout_canary_weight:g}, shadow sample "
              f"{args.rollout_shadow_sample:g}, min "
              f"{args.rollout_min_requests} gated requests)")
    print(f"fleet router on http://{args.host}:{router.port}/ over "
          f"{fleet.size()} replica(s) "
          f"(/v1/predict /v1/generate /v1/models /healthz /readyz "
          f"/metrics /fleet; ctrl-c drains the fleet and stops)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("draining fleet...")
        if rollout is not None:
            try:
                rollout.abort("serve-fleet shutdown")
            except ValueError:
                pass        # no rollout in flight
            rollout.join(timeout=30.0)
        if scaler is not None:
            scaler.stop(wait_retires=False)
        if collector is not None:
            collector.stop()
        router.stop()
        fleet.stop(drain=True)


def _cmd_fleet_status(args):
    """Render a running collector's /fleet/snapshot as the text
    dashboard — once, or forever under --watch."""
    import json as _json
    import urllib.request

    from deeplearning4j_tpu.observability.fleetobs import (
        render_status)

    base = args.collector.rstrip("/")

    def fetch():
        with urllib.request.urlopen(base + "/fleet/snapshot",
                                    timeout=5.0) as resp:
            return _json.loads(resp.read().decode("utf-8"))

    if args.watch is None:
        print(render_status(fetch()))
        return
    try:
        while True:
            try:
                text = render_status(fetch())
            except (OSError, ValueError) as exc:
                text = f"collector unreachable at {base}: {exc}"
            # clear-screen escape keeps the dashboard in place like
            # watch(1) without depending on curses
            print("\x1b[2J\x1b[H" + text, flush=True)
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        pass


def _render_rollout(st):
    lines = [
        f"state    : {st.get('state')}"
        + (f" ({st.get('outcome')})" if st.get("outcome") else ""),
        f"versions : v{st.get('incumbent_version')} -> "
        f"v{st.get('candidate_version')}",
        f"progress : {st.get('updated')}/{st.get('total')} "
        f"replica(s) updated (canary rid "
        f"{st.get('canary_rid')})",
        f"gate     : verdict={st.get('last_verdict')} "
        f"holds={st.get('holds')}"
        + (f" gate={st.get('last_gate')}"
           if st.get("last_gate") else ""),
    ]
    if st.get("last_detail"):
        lines.append(f"detail   : {st['last_detail']}")
    if st.get("incident_dir"):
        lines.append(f"incident : {st['incident_dir']}")
    return "\n".join(lines)


def _cmd_fleet_rollout(args):
    """Operator verbs over the router's /v1/rollout/* endpoints."""
    import json as _json
    import time
    import urllib.error
    import urllib.request

    base = args.router.rstrip("/")

    def call(method, path, body=None):
        data = _json.dumps(body).encode() \
            if body is not None else None
        req = urllib.request.Request(
            base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                return resp.status, _json.loads(
                    resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return e.code, _json.loads(
                    e.read().decode("utf-8"))
            except ValueError:
                return e.code, {"error": str(e)}
        except OSError as e:
            raise SystemExit(
                f"router unreachable at {base}: {e}")

    if args.verb == "start":
        status, body = call("POST", "/v1/rollout/start", {})
        if status != 200:
            raise SystemExit(
                f"start refused ({status}): "
                f"{body.get('error', body)}")
        print(_render_rollout(body))
        return
    if args.verb == "abort":
        status, body = call("POST", "/v1/rollout/abort",
                            {"reason": args.reason})
        if status != 200:
            raise SystemExit(
                f"abort refused ({status}): "
                f"{body.get('error', body)}")
        print(_render_rollout(body))
        return
    # status
    if args.watch is None:
        status, body = call("GET", "/v1/rollout/status")
        if status != 200:
            raise SystemExit(
                f"no rollout controller ({status}): "
                f"{body.get('error', body)}")
        print(_render_rollout(body))
        return
    try:
        while True:
            status, body = call("GET", "/v1/rollout/status")
            text = _render_rollout(body) if status == 200 \
                else f"no rollout controller ({status})"
            print("\x1b[2J\x1b[H" + text, flush=True)
            # outcome only lands at a terminal state (promoted /
            # rolled_back) — stop watching there
            if status == 200 and body.get("outcome") \
                    and body.get("state") not in (
                        "canary", "expanding", "rolling_back"):
                return
            time.sleep(max(0.2, args.watch))
    except KeyboardInterrupt:
        pass


def _cmd_index_build(args):
    """The offline index workload: load/synthesize a corpus, build
    the index on device, report stats (+ IVF recall vs exact), and
    optionally write the .npz corpus serve --index consumes."""
    import time as _time
    import numpy as np
    from deeplearning4j_tpu.retrieval import BruteForceIndex, IVFIndex
    ids, vectors, vocab, table = _load_corpus(args.corpus)
    dim = int(vectors.shape[1])
    t0 = _time.perf_counter()
    if args.index_kind == "ivf":
        index = IVFIndex(dim, nlist=args.nlist,
                         metric=args.index_metric)
        index.build(ids, vectors)
    else:
        index = BruteForceIndex(dim, metric=args.index_metric)
        index.add(ids, vectors)
    built_s = _time.perf_counter() - t0
    st = index.stats()
    extra = (f", {st['cells']['count']} populated cell(s) of nlist "
             f"{st['nlist']} (largest {st['cells']['max_size']})"
             if "nlist" in st else "")
    print(f"built {st['kind']}/{st['metric']}: {st['vectors']} "
          f"vector(s), dim {st['dim']}{extra} in {built_s:.2f}s")
    if args.report_recall and hasattr(index, "estimate_recall"):
        k = args.report_recall
        probes = sorted({max(1, min(n, args.nlist))
                         for n in (1, 4, 16, args.nlist)})
        for npb in probes:
            t0 = _time.perf_counter()
            r = index.estimate_recall(k=k, sample=64, nprobe=npb)
            dt = _time.perf_counter() - t0
            if r is None:
                continue
            print(f"recall@{k} nprobe={npb}: {r:.3f} "
                  f"(64-query probe, {dt:.2f}s)")
    elif args.report_recall:
        print(f"recall@{args.report_recall}: 1.000 (brute force is "
              "the exact oracle)")
    if args.out:
        payload = {"ids": np.asarray(ids), "vectors": vectors}
        if vocab is not None and table is not None:
            payload["tokens"] = np.array(
                sorted(vocab, key=vocab.get))
            payload["table"] = table
        np.savez_compressed(args.out, **payload)
        print(f"wrote {args.out}: {vectors.shape[0]} vector(s)"
              + (", embedder vocab+table included"
                 if vocab is not None else "")
              + " — load it with serve --index")


def _cmd_summary(args):
    from deeplearning4j_tpu.util.model_guesser import (guess_format,
                                                       load_model_guess)
    kind = guess_format(args.model)
    print(f"format: {kind}")
    model = load_model_guess(args.model)
    if hasattr(model, "summary"):
        print(model.summary())


def main(argv=None):
    p = argparse.ArgumentParser(prog="deeplearning4j_tpu")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="record structured spans for this run and "
                        "write a Chrome trace-event file (open in "
                        "Perfetto / chrome://tracing) to PATH on exit")
    p.add_argument("--xla-cache", metavar="DIR", default=None,
                   help="enable JAX's persistent compilation cache "
                        "rooted at DIR: compiled executables survive "
                        "process restarts, so a restarted trainer or "
                        "a fresh serving replica warms from disk "
                        "instead of cold-compiling (pairs with "
                        "--aot-warmup)")
    p.add_argument("--flight-record", metavar="DIR", default=None,
                   help="install a flight recorder: spans/stats/"
                        "anomalies ride a bounded ring and a "
                        "self-contained post-mortem bundle (JSONL + "
                        "Chrome trace + env snapshot) is written "
                        "under DIR on crash or exit")
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train a saved model on CSV data")
    t.add_argument("--model", required=True)
    t.add_argument("--data", required=True)
    t.add_argument("--label-index", type=int, required=True)
    t.add_argument("--classes", type=int, default=0,
                   help="0 = regression")
    t.add_argument("--batch-size", type=int, default=64)
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--workers", type=int, default=0,
                   help=">1 = data-parallel over that many devices "
                        "(legacy wrapper; prefer --mesh)")
    t.add_argument("--mesh", metavar="SPEC", default=None,
                   help="declarative sharded training: 'dp=4' | "
                        "'dp=2,tp=2' | JSON (axes dp/tp; sp trains "
                        "via ParallelWrapper, pp via the SPMD "
                        "pipeline module). Params are placed per "
                        "the spec, batches split over dp, and the "
                        "train step runs as ONE sharded device "
                        "program — composing with --k-step (fused "
                        "sharded windows) and --aot-warmup. On a "
                        "CPU host export XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N "
                        "first")
    t.add_argument("--prefetch", type=int, default=2)
    t.add_argument("--output", default=None)
    t.add_argument("--health", nargs="?", const="warn", default=None,
                   choices=["warn", "raise", "rollback"],
                   metavar="POLICY",
                   help="attach the training-health monitor (fused "
                        "NaN/Inf check in the train step + "
                        "divergence/plateau/gradient detectors); "
                        "POLICY = warn | raise | rollback "
                        "(default warn)")
    t.add_argument("--k-step", type=int, default=1, metavar="N",
                   help="fuse N train steps into one device program "
                        "(lax.scan over a stacked batch window): the "
                        "dispatch-bound regime pays one host "
                        "round-trip per N steps; listeners/health "
                        "still see every step, checkpoints land on "
                        "N-step boundaries (preemption resume stays "
                        "bit-identical); an epoch tail of "
                        "n_batches %% N runs through the "
                        "pre-compiled single-step program")
    t.add_argument("--aot-warmup", action="store_true",
                   help="pre-compile the train-step programs "
                        "(jit().lower(shapes).compile()) from the "
                        "first batch's shape before training: the "
                        "steady state then compiles zero times for "
                        "batches of that shape (a partial FINAL "
                        "batch — dataset size not divisible by "
                        "--batch-size — still compiles once on "
                        "first use; --xla-cache makes that one-time "
                        "across runs)")
    t.add_argument("--async-checkpoint", action="store_true",
                   help="train under ElasticTrainer with background "
                        "checkpoint writes: saves cost the train "
                        "thread a device->host snapshot only; "
                        "serialization + zip + atomic rename run on "
                        "a writer thread (SIGTERM still drains it "
                        "before the process stops); write timing "
                        "lands in checkpoint_write_seconds")
    t.add_argument("--chaos", metavar="PLAN", default=None,
                   help="install a deterministic fault-injection "
                        "plan for this run: inline JSON or a path to "
                        "a JSON file (see README 'Fault injection & "
                        "resilience' for the schema/site table); "
                        "fired faults count as "
                        "chaos_faults_fired_total")
    t.add_argument("--chaos-seed", type=int, default=None,
                   metavar="N",
                   help="seed for the fault plan's rng streams "
                        "(default: the plan's own seed, else a "
                        "recorded random one) — rerunning with the "
                        "printed seed replays the faults")
    t.set_defaults(fn=_cmd_train)

    ps = sub.add_parser(
        "train-ps",
        help="asynchronous parameter-server training: compressed-"
             "delta push/pull with bounded staleness")
    ps.add_argument("--model", required=True)
    ps.add_argument("--data", required=True)
    ps.add_argument("--label-index", type=int, required=True)
    ps.add_argument("--classes", type=int, default=0,
                    help="0 = regression")
    ps.add_argument("--batch-size", type=int, default=64)
    ps.add_argument("--epochs", type=int, default=1)
    ps.add_argument("--role",
                    choices=("launcher", "server", "worker"),
                    default="launcher",
                    help="launcher runs the server here and spawns "
                         "worker subprocesses; server/worker run one "
                         "piece each (for soaks that SIGKILL them)")
    ps.add_argument("--ps-workers", type=int, default=2,
                    help="worker subprocesses the launcher spawns")
    ps.add_argument("--connect", metavar="HOST:PORT", default=None,
                    help="(worker role) the server to join")
    ps.add_argument("--worker-index", type=int, default=0,
                    help="(worker role) this worker's shard index")
    ps.add_argument("--num-workers", type=int, default=1,
                    help="(worker role) total shard count")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--ps-port", type=int, default=0,
                    help="server listen port (0 = ephemeral)")
    ps.add_argument("--lr", type=float, default=0.05,
                    help="server-side SGD rate applied to pushed "
                         "deltas")
    ps.add_argument("--max-staleness", type=int, default=-1,
                    metavar="N",
                    help="refuse pushes based on params more than N "
                         "versions behind (-1 = unbounded async, "
                         "0 = every push must be current)")
    ps.add_argument("--push-threshold", type=float, default=0.0,
                    help="EF sparsification threshold (entries with "
                         "|g+residual| below it wait in the "
                         "residual; the reference's "
                         "ThresholdAlgorithm knob)")
    ps.add_argument("--ckpt-dir", default=None,
                    help="durable-generation directory (default "
                         "OUTPUT.ps-ckpts); a restarted server "
                         "resumes from the newest intact one")
    ps.add_argument("--save-every", type=int, default=50,
                    metavar="N", help="checkpoint every N applied "
                                      "pushes (async, off the "
                                      "serving path)")
    ps.add_argument("--heartbeat-timeout", type=float, default=3.0,
                    metavar="S",
                    help="retire a worker silent for S seconds")
    ps.add_argument("--op-timeout", type=float, default=2.0,
                    metavar="S",
                    help="per-op client deadline before "
                         "reconnect+retry")
    ps.add_argument("--output", default=None)
    ps.add_argument("--chaos", metavar="PLAN", default=None,
                    help="deterministic fault plan (sites "
                         "ps.push.drop / ps.pull.timeout / "
                         "ps.server.restart)")
    ps.add_argument("--chaos-seed", type=int, default=None,
                    metavar="N")
    ps.add_argument("--net-chaos", metavar="PLAN", default=None,
                    help="deterministic NETWORK plan on the DPS1 "
                         "wire (launcher role): workers dial a "
                         "seeded TCP fault proxy (site net.ps) "
                         "instead of the server directly")
    ps.add_argument("--net-chaos-seed", type=int, default=None,
                    metavar="N")
    ps.set_defaults(fn=_cmd_train_ps)

    u = sub.add_parser("ui", help="training dashboard server")
    u.add_argument("--port", type=int, default=9000)
    u.add_argument("--stats-file", default=None)
    u.set_defaults(fn=_cmd_ui)

    k = sub.add_parser("serve-knn", help="k-NN REST server")
    k.add_argument("--points", required=True)
    k.add_argument("--port", type=int, default=9200)
    k.add_argument("--distance", default="euclidean",
                   choices=["euclidean", "cosine"])
    k.set_defaults(fn=_cmd_serve_knn)

    v = sub.add_parser(
        "serve",
        help="model-serving HTTP server (dynamic + continuous "
             "batching, admission control, /metrics)")
    v.add_argument("--model", action="append", required=False,
                   metavar="[NAME=]PATH",
                   help="model zip to host; repeatable; NAME defaults "
                        "to 'default'")
    v.add_argument("--host", default="127.0.0.1")
    v.add_argument("--port", type=int, default=8080)
    v.add_argument("--max-batch-size", type=int, default=32,
                   help="rows per coalesced predict call")
    v.add_argument("--queue-limit", type=int, default=256,
                   help="pending requests before load-shed (429)")
    v.add_argument("--wait-ms", type=float, default=2.0,
                   help="batch collection window")
    v.add_argument("--slots", type=int, default=4,
                   help="continuous-batching KV-cache slots")
    v.add_argument("--capacity", type=int, default=256,
                   help="max prompt+generated tokens per request")
    v.add_argument("--kv-mode", choices=("auto", "paged", "dense"),
                   default="auto",
                   help="decode KV cache: 'paged' = refcounted page "
                        "pool + prefix cache (slot count bounded by "
                        "memory), 'dense' = per-slot capacity "
                        "buckets, 'auto' pages transformer models "
                        "and falls back to dense for recurrent ones")
    v.add_argument("--page-size", type=int, default=16,
                   help="tokens per KV page (paged mode)")
    v.add_argument("--kv-pages", type=int, default=None,
                   help="total pages in the pool (default: memory "
                        "parity with the dense session, "
                        "slots * ceil(capacity/page_size))")
    v.add_argument("--trace-sample", type=float, default=0.01,
                   metavar="RATE",
                   help="head-based request-trace sampling rate in "
                        "[0, 1] (default 0.01); deterministic in the "
                        "trace id, honours inbound W3C traceparent "
                        "headers, errors always sampled")
    v.add_argument("--slow-ms", type=float, default=250.0,
                   help="requests at or above this duration land in "
                        "the /debug/traces slow ring")
    v.add_argument("--aot-warmup", action="store_true",
                   help="pre-compile every hosted model's serving "
                        "executables at boot (predict pow2 batch "
                        "buckets up to --max-batch-size + a generate "
                        "prefill/decode pass): the first real "
                        "request never pays an XLA compile")
    v.add_argument("--slo", metavar="RULES", default=None,
                   help="declarative SLOs: inline JSON or a JSON "
                        "file (see README 'Request tracing & SLOs' "
                        "for the rule schema); multi-window burn-rate "
                        "breaches flip /healthz to degraded")
    v.add_argument("--mesh", metavar="SPEC", default=None,
                   help="serve predict tensor-parallel over a "
                        "declarative mesh ('tp=2' | 'dp=2,tp=2'): "
                        "params sharded per the Megatron rule "
                        "table, one AOT-warmable executable per "
                        "pow2 batch bucket; the mesh shape is "
                        "surfaced on /healthz and the "
                        "serving_mesh_devices gauge")
    _add_index_flags(v)
    v.set_defaults(fn=_cmd_serve)

    f = sub.add_parser(
        "serve-fleet",
        help="N-replica serving fleet behind the health-aware "
             "router (failover, hedging, session affinity, "
             "zero-downtime drain)")
    f.add_argument("--model", action="append", required=False,
                   metavar="[NAME=]PATH",
                   help="model zip hosted on EVERY replica; "
                        "repeatable")
    f.add_argument("--replicas", type=int, default=2,
                   help="fleet size (in-process ModelServer "
                        "replicas on loopback ports)")
    f.add_argument("--host", default="127.0.0.1")
    f.add_argument("--port", type=int, default=8080,
                   help="the ROUTER's port (replicas pick free "
                        "loopback ports)")
    f.add_argument("--max-batch-size", type=int, default=32)
    f.add_argument("--queue-limit", type=int, default=256)
    f.add_argument("--wait-ms", type=float, default=2.0)
    f.add_argument("--slots", type=int, default=4)
    f.add_argument("--capacity", type=int, default=256)
    f.add_argument("--roles", metavar="SPEC", default=None,
                   help="disaggregated prefill/decode serving: "
                        "per-replica roles as 'prefill=1,decode=3' "
                        "(counts must sum to --replicas; roles are "
                        "prefill / decode / mixed). A prefill "
                        "replica runs prompts and exports KV leases "
                        "(/v1/kv/export); the router rebuilds them "
                        "on a decode replica (/v1/kv/import) which "
                        "streams the completion — token-identical "
                        "to a single-replica run")
    f.add_argument("--kv-mode", choices=("auto", "paged", "dense"),
                   default="auto",
                   help="replica decode KV mode (see serve "
                        "--kv-mode); disaggregation and prefix-"
                        "aware routing need the paged path")
    f.add_argument("--page-size", type=int, default=16,
                   help="tokens per KV page on every replica")
    f.add_argument("--kv-pages", type=int, default=None,
                   help="KV pool pages per replica (default: "
                        "memory parity with the dense session)")
    f.add_argument("--no-kv-routing", action="store_true",
                   help="disable prefix-aware generate routing "
                        "(affinity + least-loaded only — the bench "
                        "baseline)")
    f.add_argument("--probe-interval", type=float, default=1.0,
                   metavar="S",
                   help="active health-probe period (seconds)")
    f.add_argument("--hedge-after-ms", type=float, default=750.0,
                   help="fire a hedged /v1/predict on a second "
                        "replica after this quiet interval; <= 0 "
                        "disables hedging")
    f.add_argument("--trace-sample", type=float, default=0.01,
                   metavar="RATE")
    f.add_argument("--mesh", metavar="SPEC", default=None,
                   help="every replica serves predict tensor-"
                        "parallel over this mesh spec (see serve "
                        "--mesh); replica meshes surface on each "
                        "/healthz the router scrapes")
    f.add_argument("--chaos", metavar="PLAN", default=None,
                   help="deterministic fault plan (the "
                        "serving.replica site kills/hangs whole "
                        "replicas mid-load; serving.replica.boot "
                        "fails/stalls scale-up boots)")
    f.add_argument("--chaos-seed", type=int, default=None,
                   metavar="N")
    f.add_argument("--net-chaos", metavar="PLAN", default=None,
                   help="deterministic NETWORK plan: every replica "
                        "boots behind a seeded TCP fault proxy "
                        "(site net.replica; kinds partition/reset/"
                        "truncate/corrupt/delay/throttle/half_open "
                        "— see README 'Network fault injection')")
    f.add_argument("--net-chaos-seed", type=int, default=None,
                   metavar="N")
    f.add_argument("--autoscale", metavar="MIN:MAX", default=None,
                   help="run the SLO-driven autoscaler over the "
                        "fleet: replica count moves inside "
                        "[MIN, MAX] from SLO burn rate + queue "
                        "depth + KV pressure (boot-first scale-up, "
                        "drain-based scale-down of the replica "
                        "with the fewest pinned streams)")
    f.add_argument("--autoscale-tick", type=float, default=1.0,
                   metavar="S",
                   help="autoscaler control-loop period (seconds)")
    f.add_argument("--queue-high", type=float, default=8.0,
                   help="mean OUTSTANDING work per replica (probed "
                        "backend queue depth + router in-flight — "
                        "a queued request appears in both) above "
                        "which the autoscaler votes scale-up")
    f.add_argument("--queue-low", type=float, default=1.0,
                   help="mean outstanding work per replica below "
                        "which the autoscaler votes scale-down")
    f.add_argument("--slo", metavar="RULES", default=None,
                   help="declarative SLOs evaluated over the "
                        "ROUTER's latency/availability metrics "
                        "(inline JSON or @file; see README "
                        "'Request tracing & SLOs'); burn-rate "
                        "breaches are the autoscaler's primary "
                        "scale-up trigger. Use metric "
                        "'router_latency_seconds' with labels "
                        "{'route': '/v1/predict'} for latency "
                        "objectives at the router")
    f.add_argument("--collector", type=int, default=None,
                   metavar="PORT",
                   help="run the fleet observability collector on "
                        "this port (0 picks a free one): scrapes "
                        "every member's /metrics each interval, "
                        "re-exposes the merged fleet registry, "
                        "stitches cross-process traces, and writes "
                        "incident bundles on fleet-SLO breach or "
                        "replica death. Read it with 'fleet-status "
                        "--collector URL'")
    f.add_argument("--collector-interval", type=float, default=1.0,
                   metavar="S",
                   help="collector scrape period (seconds)")
    f.add_argument("--incident-dir", default=None, metavar="DIR",
                   help="where the collector writes incident-scoped "
                        "fleet bundles (default: cwd)")
    f.add_argument("--rollout", action="append", default=None,
                   metavar="[NAME=]PATH",
                   help="stage a CANDIDATE model zip for an SLO-"
                        "gated canary rollout (repeatable, same "
                        "spec format as --model). The controller "
                        "arms but does NOT deploy: trigger it with "
                        "'fleet-rollout start'. Requires "
                        "--collector — promotion needs the merged "
                        "replica-labeled series as gate evidence")
    f.add_argument("--rollout-version", type=int, default=None,
                   metavar="N",
                   help="candidate model version (default: "
                        "incumbent + 1)")
    f.add_argument("--rollout-canary-weight", type=float,
                   default=0.25, metavar="FRAC",
                   help="deterministic traffic share hashed to the "
                        "canary during the gate window (trace-id-"
                        "sticky: a request's retries and hedges "
                        "stay on-version)")
    f.add_argument("--rollout-shadow-sample", type=float,
                   default=0.5, metavar="FRAC",
                   help="mirror this fraction of predict traffic "
                        "to the canary and score its answers "
                        "against the primary's (never returned to "
                        "clients); 0 disables shadow scoring")
    f.add_argument("--rollout-min-requests", type=int, default=50,
                   metavar="N",
                   help="minimum candidate-cohort requests inside "
                        "the gate window before the comparative "
                        "SLO gate may pass (below it the rollout "
                        "HOLDS — no wall-clock-only promotion)")
    _add_index_flags(f)
    f.set_defaults(fn=_cmd_serve_fleet)

    fs = sub.add_parser(
        "fleet-status",
        help="one-shot (or --watch) dashboard over a running fleet "
             "collector's /fleet/snapshot")
    fs.add_argument("--collector", default="http://127.0.0.1:9290",
                    metavar="URL",
                    help="base URL of the collector started by "
                         "serve-fleet --collector")
    fs.add_argument("--watch", type=float, default=None, metavar="S",
                    help="refresh every S seconds until ctrl-c "
                         "instead of printing once")
    fs.set_defaults(fn=_cmd_fleet_status)

    fr = sub.add_parser(
        "fleet-rollout",
        help="drive the canary rollout armed by serve-fleet "
             "--rollout: start it, watch its gate verdicts, or "
             "abort into an automatic rollback")
    fr.add_argument("verb", choices=("start", "status", "abort"),
                    help="start = begin the canary deployment; "
                         "status = one-shot (or --watch) state/"
                         "gate dump; abort = roll every updated "
                         "replica back to the incumbent")
    fr.add_argument("--router", default="http://127.0.0.1:8080",
                    metavar="URL",
                    help="base URL of the fleet router (the "
                         "controller answers on /v1/rollout/*)")
    fr.add_argument("--reason", default="operator abort",
                    help="abort reason recorded in the incident "
                         "bundle (abort only)")
    fr.add_argument("--watch", type=float, default=None, metavar="S",
                    help="with 'status': refresh every S seconds "
                         "until ctrl-c or the rollout reaches a "
                         "terminal state")
    fr.set_defaults(fn=_cmd_fleet_rollout)

    ix = sub.add_parser(
        "index",
        help="vector-index workloads (build / recall report)")
    ixsub = ix.add_subparsers(dest="index_cmd", required=True)
    ib = ixsub.add_parser(
        "build",
        help="build an index from a corpus, report recall, write "
             "the .npz serve --index loads")
    ib.add_argument("--corpus", required=True, metavar="SPEC",
                    help="'random:n=4096,dim=64,seed=0,clusters=32' "
                         "or an existing .npz with vectors[+ids]"
                         "[+tokens/table]")
    ib.add_argument("--out", default=None, metavar="FILE",
                    help="write the corpus as .npz (ids, vectors "
                         "[, tokens, table]) for serve --index")
    ib.add_argument("--index-kind", choices=("brute", "ivf"),
                    default="ivf")
    ib.add_argument("--nlist", type=int, default=16,
                    help="IVF cell count")
    ib.add_argument("--index-metric",
                    choices=("cosine", "dot", "euclidean"),
                    default="cosine")
    ib.add_argument("--report-recall", type=int, default=10,
                    metavar="K",
                    help="estimate recall@K vs the exact answer "
                         "over a seeded 64-query probe (0 skips)")
    ib.set_defaults(fn=_cmd_index_build)

    s = sub.add_parser("summary", help="inspect a model file")
    s.add_argument("--model", required=True)
    s.set_defaults(fn=_cmd_summary)

    args = p.parse_args(argv)
    if args.xla_cache:
        # must land before first backend use: the persistent cache is
        # consulted at compile time, AOT warmup included
        import jax
        os.makedirs(args.xla_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", args.xla_cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    recorder = None
    if args.flight_record:
        from deeplearning4j_tpu.observability.flight_recorder import (
            FlightRecorder, install)
        from deeplearning4j_tpu.observability.tracing import trace
        trace.enable()     # spans must flow for trace.json to matter
        recorder = install(FlightRecorder(out_dir=args.flight_record))
    if args.trace:
        import atexit

        from deeplearning4j_tpu.observability.tracing import trace
        trace.enable()

        def _dump(path=args.trace):
            n = trace.export_chrome_trace(path)
            print(f"trace written: {path} ({n} events)")

        atexit.register(_dump)
    try:
        args.fn(args)
    except Exception:
        if recorder is not None:
            # the fit-loop hook usually dumped already (forced);
            # debounce here so a CLI-level crash still leaves a
            # bundle without duplicating the fit-loop one
            recorder.dump("cli_exception", force=False)
        raise
    else:
        if recorder is not None:
            bundle = recorder.dump("exit", force=True)
            if bundle:
                print(f"flight-recorder bundle: {bundle}")


if __name__ == "__main__":
    main()
