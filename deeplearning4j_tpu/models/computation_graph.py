"""ComputationGraph: the DAG executor.

TPU rewrite of nn/graph/ComputationGraph.java (3350 LoC): forward walks
the cached topological order (reference :1187, fan-out at :817);
training is one jitted step over the whole DAG — multi-input,
multi-output, summed output losses (reference computeGradientAndScore
:1295 sums output-layer scores).

Params/state are dicts keyed by vertex name (the reference keeps a
params view array per vertex; a name-keyed pytree is the JAX-native
equivalent and checkpoint-stable).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn.conf import updaters as updaters_mod
from deeplearning4j_tpu.models.kstep import KStepExecutorMixin
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.train.constraints import apply_layer_constraints

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ComputationGraph"]


class ComputationGraph(KStepExecutorMixin):
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Optional[Dict[str, dict]] = None
        self.state: Optional[Dict[str, dict]] = None
        self.opt_state = None
        self.listeners = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value = float("nan")
        self._rng_key = None
        self._optimizer = None
        self._jit_train_step = None
        self._jit_tbptt_step = None
        # k-step fused programs (models/kstep.py): dict k -> jitted
        # scan program, plus AOT-compiled executables keyed by batch
        # signature (warmup() fills; the fit loop dispatches them
        # directly so the steady state never traces or compiles)
        self._jit_kstep: Dict[int, Any] = {}
        self._aot: Dict[tuple, Any] = {}
        self._jit_output = {}
        self._rnn_state: Optional[Dict[str, object]] = None
        # (data_wait_s, dispatch_s) of the latest fit iteration —
        # read by observability.step_profile.ProfilerListener
        self._step_timing = None
        # observability.health wiring (see MultiLayerNetwork): fused
        # finite-check vector stashed unfetched + latest batch refs
        self._health_enabled = False
        self._last_health = None
        self._last_batch = None

    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        seed = self.conf.conf.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        self._rng_key = jax.random.fold_in(key, 0xC6)
        order = self.conf.topological_order()
        params, states = {}, {}
        keys = jax.random.split(key, max(len(order), 1))
        for k, name in zip(keys, order):
            obj, ins = self.conf.vertices[name]
            if isinstance(obj, Layer):
                it = self.conf.vertex_input_type(name)
                p, s = obj.initialize(k, it)
                params[name] = p
                states[name] = s
        self.params = params
        self.state = states
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        global_cfg = self.conf.conf.updater_cfg or updaters_mod.sgd()
        overrides = {name: getattr(obj, "updater", None)
                     for name, (obj, _) in self.conf.vertices.items()
                     if isinstance(obj, Layer)
                     and getattr(obj, "updater", None) is not None}
        if overrides:
            transforms = {"__global__": updaters_mod.to_optax(global_cfg)}
            labels = {}
            for name in self.params:
                if name in overrides:
                    transforms[name] = updaters_mod.to_optax(overrides[name])
                    tag = name
                else:
                    tag = "__global__"
                labels[name] = jax.tree_util.tree_map(lambda _: tag,
                                                      self.params[name])
            self._optimizer = optax.multi_transform(transforms, labels)
        else:
            self._optimizer = updaters_mod.to_optax(global_cfg)
        clip = self.conf.conf.gradient_clip
        if clip is not None:
            pre = (optax.clip_by_global_norm(clip["v"])
                   if clip["type"] == "norm" else optax.clip(clip["v"]))
            self._optimizer = optax.chain(pre, self._optimizer)
        self.opt_state = self._optimizer.init(self.params)
        self._jit_train_step = None
        self._jit_tbptt_step = None
        self._jit_kstep = {}
        self._aot = {}
        self._jit_output = {}

    # ------------------------------------------------------------------
    def _forward(self, params, state, inputs: Sequence, *, training, rng,
                 fmasks=None, exclude_outputs: bool = False, carries=None,
                 only=None):
        """Topo-order interpreter (reference ComputationGraph.java
        :793-817). Masks are routed per vertex via
        ``GraphVertex.propagate_mask`` (reference feedForwardMaskArrays
        per vertex impl), NOT first-non-None-input. ``carries``: dict
        vertex-name -> recurrent (h, c) initial state, used by tBPTT to
        carry hidden state across chunks (reference
        rnnActivateUsingStoredState :2219). Returns (activations dict,
        new state dict, new carries dict)."""
        from deeplearning4j_tpu.nn.conf.graph import (
            LastTimeStepVertex, combine_masks_or)
        acts: Dict[str, jnp.ndarray] = dict(
            zip(self.conf.network_inputs, inputs))
        masks: Dict[str, Optional[jnp.ndarray]] = {
            n: None for n in self.conf.network_inputs}
        if fmasks is not None:
            masks.update(zip(self.conf.network_inputs, fmasks))
        new_state = {}
        new_carries = {} if carries is not None else None
        for vidx, name in enumerate(self.conf.topological_order()):
            if only is not None and name not in only:
                continue        # pretrain: only the ancestor subgraph
            obj, ins = self.conf.vertices[name]
            xs = [acts[i] for i in ins]
            in_masks = [masks.get(i) for i in ins]
            if isinstance(obj, Layer):
                # a layer vertex consumes its (single) wired input's mask
                in_mask = in_masks[0]
                if exclude_outputs and name in self.conf.network_outputs \
                        and obj.has_loss():
                    # leave the loss layer's input available instead
                    acts[name] = xs[0]
                    new_state[name] = state[name]
                    masks[name] = in_mask
                    continue
                # stable per-vertex rng: topo index, NOT hash(name)
                # (python hash is per-process randomized)
                lrng = (jax.random.fold_in(rng, vidx)
                        if rng is not None else None)
                from deeplearning4j_tpu.nn.errors import (
                    layer_error_context)
                with layer_error_context(f"vertex '{name}'", obj, xs[0]):
                    if carries is not None and \
                            isinstance(obj, BaseRecurrentLayer):
                        c0 = carries.get(name)
                        if c0 is None:
                            c0 = obj.zero_state(xs[0].shape[0])
                        xd = obj.apply_input_dropout(xs[0],
                                                     training=training,
                                                     rng=lrng)
                        y, c1 = obj.apply_rnn(params[name], xd, c0,
                                              training=training, rng=lrng,
                                              mask=in_mask)
                        new_carries[name] = c1
                        s = state[name]
                    else:
                        y, s = obj.apply(params[name], state[name], xs[0],
                                         training=training, rng=lrng,
                                         mask=in_mask)
                new_state[name] = s
                acts[name] = y
                # a layer that collapses the time dimension (e.g.
                # GlobalPooling) must null the propagated (B, T) mask —
                # mirrors the reference's per-layer feedForwardMaskArray
                # (round-2 advisor): downstream consumers would get a
                # stale wrong-shaped mask otherwise
                if (in_mask is not None and (y.ndim < 3
                        or y.shape[1] != in_mask.shape[1])):
                    masks[name] = None
                else:
                    masks[name] = in_mask
            else:
                from deeplearning4j_tpu.nn.errors import (
                    layer_error_context)
                if isinstance(obj, LastTimeStepVertex) and \
                        obj.mask_input is not None:
                    use_mask = masks.get(obj.mask_input)
                else:
                    use_mask = combine_masks_or(in_masks)
                with layer_error_context(f"vertex '{name}'", obj,
                                         xs[0] if xs else None):
                    acts[name] = obj.apply(xs, mask=use_mask)
                masks[name] = obj.propagate_mask(in_masks, xs,
                                                 mask_env=masks)
        return acts, new_state, new_carries

    def _loss(self, params, state, batch, rng, *, training=True,
              carries=None):
        inputs, labels, fmasks, lmasks = batch
        acts, new_state, new_carries = self._forward(
            params, state, inputs, training=training, rng=rng,
            fmasks=fmasks, exclude_outputs=True, carries=carries)
        from deeplearning4j_tpu.nn.conf.layers.output import (
            CenterLossOutputLayer)
        total = jnp.zeros(())
        topo = self.conf.topological_order()
        for i, out_name in enumerate(self.conf.network_outputs):
            obj, ins = self.conf.vertices[out_name]
            if isinstance(obj, Layer) and obj.has_loss():
                lrng = (jax.random.fold_in(rng, 1000 + topo.index(out_name))
                        if rng is not None else None)
                lmask = lmasks[i] if lmasks is not None else None
                total = total + obj.loss_from_input(
                    params[out_name], acts[out_name], labels[i],
                    training=training, rng=lrng, mask=lmask)
                if isinstance(obj, CenterLossOutputLayer):
                    total = total + obj.lambda_ * obj.center_loss(
                        state[out_name], acts[out_name], labels[i])
                    new_state[out_name] = obj.update_centers(
                        state[out_name], acts[out_name], labels[i])
            else:
                raise ValueError(f"Output vertex '{out_name}' has no loss")
        for name, (obj, _) in self.conf.vertices.items():
            if isinstance(obj, Layer):
                total = total + obj.regularization_loss(params[name])
        if carries is not None:
            return total, (new_state, new_carries)
        return total, new_state

    def _train_core(self, params, state, opt_state, batch, rng):
        """Traced single-step training math over the whole DAG —
        shared verbatim by the k=1 jitted step and the k-step
        ``lax.scan`` body (models/kstep.py), so the fused and
        per-step programs compute bit-identical updates."""
        optimizer = self._optimizer

        def loss_fn(p):
            return self._loss(p, state, batch, rng, training=True)

        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        from deeplearning4j_tpu.train.gradnorm import (
            apply_gradient_normalization)
        layer_cfgs = {n: v[0] for n, v in self.conf.vertices.items()
                      if n in params}
        grads = apply_gradient_normalization(layer_cfgs, grads)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        constrained = {}
        for name, p in new_params.items():
            obj, _ = self.conf.vertices[name]
            constrained[name] = apply_layer_constraints(obj, p)
        if self._health_enabled:
            # fused finite check + global norms, computed inside
            # this same XLA program (observability/health.py)
            from deeplearning4j_tpu.observability.health import (
                fused_health)
            health = fused_health(loss, grads, updates, constrained)
            return constrained, new_state, new_opt, loss, health
        return constrained, new_state, new_opt, loss

    def _make_train_step(self):
        core = self._train_core

        # under a mesh context the program's output layout is pinned
        # to the placed model's (kstep._train_jit_kwargs) — GSPMD
        # must not drift a carry sharding and recompile every step
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                           **self._train_jit_kwargs())
        def train_step(params, state, opt_state, batch, base_rng, step):
            rng = jax.random.fold_in(base_rng, step)
            return core(params, state, opt_state, batch, rng)

        return train_step

    def _sync_health_mode(self) -> None:
        """Compile the fused health check into the train step iff a
        health-monitoring listener is attached."""
        want = any(getattr(l, "wants_device_health", False)
                   for l in self.listeners)
        if want != self._health_enabled:
            self._health_enabled = want
            self._jit_train_step = None
            self._jit_tbptt_step = None
            # the k-step programs' output structure includes the
            # stacked health block iff enabled — rebuild them too
            self._jit_kstep = {}
            self._aot = {}
            if not want:
                self._last_health = None

    def _make_tbptt_step(self):
        """Graph tBPTT step (reference ComputationGraph.doTruncatedBPTT
        :2532, dispatched from fit :928/:1031): recurrent vertex state
        carries across chunks, gradients are truncated at the chunk
        boundary via stop_gradient."""
        optimizer = self._optimizer

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def tbptt_step(params, state, opt_state, batch, carries, base_rng,
                       step):
            rng = jax.random.fold_in(base_rng, step)
            carries = jax.lax.stop_gradient(carries)

            def loss_fn(p):
                return self._loss(p, state, batch, rng, training=True,
                                  carries=carries)

            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            from deeplearning4j_tpu.train.gradnorm import (
                apply_gradient_normalization)
            layer_cfgs = {n: v[0] for n, v in self.conf.vertices.items()
                          if n in params}
            grads = apply_gradient_normalization(layer_cfgs, grads)
            updates, new_opt = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            constrained = {}
            for name, p in new_params.items():
                obj, _ = self.conf.vertices[name]
                constrained[name] = apply_layer_constraints(obj, p)
            return (constrained, new_state, new_opt, loss,
                    jax.lax.stop_gradient(new_carries))

        return tbptt_step

    # ------------------------------------------------------------------
    def _as_multi(self, ds) -> MultiDataSet:
        if isinstance(ds, MultiDataSet):
            return ds
        if isinstance(ds, DataSet):
            return MultiDataSet(
                [ds.features], [ds.labels],
                [ds.features_mask] if ds.features_mask is not None else None,
                [ds.labels_mask] if ds.labels_mask is not None else None)
        raise TypeError(type(ds))

    def _batch_tuple(self, mds: MultiDataSet):
        inputs = tuple(jnp.asarray(f) for f in mds.features)
        labels = tuple(jnp.asarray(l) for l in mds.labels)
        fm = (tuple(None if m is None else jnp.asarray(m)
                    for m in mds.features_masks)
              if mds.features_masks is not None else None)
        lm = (tuple(None if m is None else jnp.asarray(m)
                    for m in mds.labels_masks)
              if mds.labels_masks is not None else None)
        return (inputs, labels, fm, lm)

    def _batch_tuple_np(self, mds: MultiDataSet):
        """Host-side batch tuple (numpy, no device transfer, dtypes
        JAX-canonicalized so AOT cache keys match what the program
        actually receives): the unit the k-step window stacker works
        on."""
        from deeplearning4j_tpu.models.kstep import canonical_np
        inputs = tuple(canonical_np(f) for f in mds.features)
        labels = tuple(canonical_np(l) for l in mds.labels)
        fm = (tuple(None if m is None else canonical_np(m)
                    for m in mds.features_masks)
              if mds.features_masks is not None else None)
        lm = (tuple(None if m is None else canonical_np(m)
                    for m in mds.labels_masks)
              if mds.labels_masks is not None else None)
        return (inputs, labels, fm, lm)

    def fit(self, data, *, epochs: int = 1,
            steps_per_device_call: int = 1, mesh_spec=None):
        """data: iterable of DataSet/MultiDataSet, or a single one.
        ``steps_per_device_call=k`` fuses k train steps into one
        ``lax.scan`` device program (see
        :meth:`MultiLayerNetwork.fit`); the epoch tail runs through
        the pre-compiled k=1 program. ``mesh_spec`` trains sharded
        over a declarative device mesh and composes with the fused
        windows (see :meth:`MultiLayerNetwork.fit` /
        ``parallel/mesh_spec.py``)."""
        from deeplearning4j_tpu.observability.tracing import trace
        k = int(steps_per_device_call)
        if k < 1:
            raise ValueError("steps_per_device_call must be >= 1")
        if mesh_spec is not None:
            self.use_mesh(mesh_spec)
        if self.params is None:
            self.init()
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        elif not isinstance(data, (list, tuple)) and \
                not hasattr(data, "reset"):
            # one-shot generators would be exhausted after epoch 1;
            # materialize so every epoch actually trains
            data = list(data)
        self._sync_health_mode()
        if self._jit_train_step is None:
            self._jit_train_step = self._make_train_step()
        tbptt = self.conf.conf.tbptt
        try:
            for _ in range(epochs):
                with trace.span("epoch"):
                    for lst in self.listeners:
                        lst.on_epoch_start(self)
                    self._fit_epoch(iter(data), k, tbptt)
                    for lst in self.listeners:
                        lst.on_epoch_end(self)
                self.epoch_count += 1
        except Exception as e:
            # black box: leave a post-mortem bundle when a flight
            # recorder is installed, then propagate unchanged
            from deeplearning4j_tpu.observability.flight_recorder \
                import on_fit_exception
            on_fit_exception(self, e)
            raise
        return self

    # KStepExecutorMixin adapters (fit_batches/_fit_one live there)
    def _coerce_fit_batch(self, ds) -> MultiDataSet:
        return self._as_multi(ds)

    def _batch_is_tbptt(self, mds: MultiDataSet, tbptt) -> bool:
        return tbptt is not None and any(np.ndim(f) == 3
                                         for f in mds.features)

    def _run_tbptt(self, mds: MultiDataSet, tbptt,
                   data_wait_s: float = 0.0) -> None:
        self._fit_tbptt(mds, tbptt, data_wait_s=data_wait_s)

    def warmup(self, example, *, steps_per_device_call: int = 1,
               mesh_spec=None):
        """AOT warmup: ``jit(...).lower(shapes).compile()`` the
        k-step and k=1 train programs for this batch signature (see
        :meth:`MultiLayerNetwork.warmup`). Attach listeners before
        warming. Returns ``{program: compile_seconds}``."""
        from deeplearning4j_tpu.models import kstep as _kstep
        if mesh_spec is not None:
            self.use_mesh(mesh_spec)
        if self.params is None:
            self.init()
        self._sync_health_mode()
        if self._jit_train_step is None:
            self._jit_train_step = self._make_train_step()
        batch_np = self._batch_tuple_np(self._as_multi(example))
        return _kstep.warmup_train_programs(
            self, batch_np, int(steps_per_device_call))

    def _fit_tbptt(self, mds: MultiDataSet, tbptt,
                   data_wait_s: float = 0.0):
        """Truncated BPTT over a MultiDataSet (reference
        ComputationGraph.doTruncatedBPTT :2532): every time-series
        array (features, labels, masks) is split into fwd_length
        chunks; recurrent vertex hidden state carries across chunks
        with the gradient stopped at the boundary. ``data_wait_s`` is
        billed to the first chunk's ``_step_timing``."""
        import time
        fwd = tbptt["fwd_length"]
        ts = [f for f in mds.features if np.ndim(f) == 3]
        T = ts[0].shape[1]
        B = ts[0].shape[0]
        # the tBPTT step has no fused health vector: a stale one from
        # the standard path must not masquerade as this chunk's
        self._last_health = None
        if self._jit_tbptt_step is None:
            self._jit_tbptt_step = self._make_tbptt_step()
        step_fn = self._jit_tbptt_step
        carries = {name: obj.zero_state(B)
                   for name, (obj, _) in self.conf.vertices.items()
                   if isinstance(obj, BaseRecurrentLayer)}

        for start in range(0, T, fwd):
            end = min(start + fwd, T)
            feats = tuple(f[:, start:end] if np.ndim(f) == 3 else f
                          for f in mds.features)
            labels = tuple(l[:, start:end] if np.ndim(l) == 3 else l
                           for l in mds.labels)
            fm = (tuple(None if m is None
                        else (m[:, start:end]
                              if np.ndim(m) == 2 and m.shape[1] == T
                              else m)
                        for m in mds.features_masks)
                  if mds.features_masks is not None else None)
            lm = (tuple(None if m is None
                        else (m[:, start:end]
                              if np.ndim(m) == 2 and m.shape[1] == T
                              else m)
                        for m in mds.labels_masks)
                  if mds.labels_masks is not None else None)
            sub = MultiDataSet(list(feats), list(labels),
                               None if fm is None else list(fm),
                               None if lm is None else list(lm))
            t_chunk = time.perf_counter()
            batch = self._batch_tuple(sub)
            (self.params, self.state, self.opt_state, loss,
             carries) = step_fn(self.params, self.state, self.opt_state,
                                batch, carries, self._rng_key,
                                np.int32(self.iteration_count))
            self.score_value = loss
            self._step_timing = (data_wait_s if start == 0 else 0.0,
                                 time.perf_counter() - t_chunk)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count, loss,
                                   sub.num_examples())
            self.iteration_count += 1

    # ------------------------------------------------------------------
    def output(self, *inputs, training: bool = False, input_masks=None):
        if self.params is None:
            self.init()
        xs = tuple(jnp.asarray(x) for x in inputs)
        fmasks = (tuple(None if m is None else jnp.asarray(m)
                        for m in input_masks)
                  if input_masks is not None else None)
        key = (training, fmasks is not None)
        if key not in self._jit_output:
            @jax.jit
            def fwd(params, state, xs, rng, fmasks):
                acts, _, _ = self._forward(params, state, xs,
                                           training=training, rng=rng,
                                           fmasks=fmasks)
                return tuple(acts[o] for o in self.conf.network_outputs)
            self._jit_output[key] = fwd
        rng = self._next_call_rng() if training else None
        outs = self._jit_output[key](self.params, self.state, xs, rng,
                                     fmasks)
        return outs if len(outs) > 1 else outs[0]

    def _next_call_rng(self):
        # fold a per-call counter into the key: repeated training-mode
        # forward passes (MC-dropout sampling) must draw FRESH dropout
        # masks, not N identical ones (round-2 advisor, medium)
        self._output_calls = getattr(self, "_output_calls", 0) + 1
        return jax.random.fold_in(self._rng_key, self._output_calls)

    def feed_forward(self, *inputs, training: bool = False,
                     input_masks=None):
        xs = tuple(jnp.asarray(x) for x in inputs)
        acts, _, _ = self._forward(self.params, self.state, xs,
                                   training=training,
                                   rng=(self._next_call_rng()
                                        if training else None),
                                   fmasks=input_masks)
        return acts

    def score(self, ds) -> float:
        mds = self._as_multi(ds)
        loss, _ = self._loss(self.params, self.state,
                             self._batch_tuple(mds), None, training=False)
        return float(loss)

    def _iter_pred_batches(self, data):
        """Shared eval iteration: one forward per batch, ALL heads."""
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        for ds in data:
            mds = self._as_multi(ds)
            preds = self.output(*mds.features,
                                input_masks=mds.features_masks)
            if not isinstance(preds, tuple):
                preds = (preds,)
            yield mds, preds

    @staticmethod
    def _eval_one(ev, labels, preds, mask):
        try:
            ev.eval(labels, preds, mask=mask)
        except TypeError:         # evaluators without mask support (ROC)
            ev.eval(labels, preds)

    def _eval_with(self, data, ev, output_index: int = 0):
        for mds, preds in self._iter_pred_batches(data):
            lmask = (mds.labels_masks[output_index]
                     if mds.labels_masks is not None else None)
            self._eval_one(ev, mds.labels[output_index],
                           np.asarray(preds[output_index]), lmask)
        return ev

    def evaluate(self, data, output_index: int = 0):
        from deeplearning4j_tpu.evaluation.classification import Evaluation
        return self._eval_with(data, Evaluation(), output_index)

    def evaluate_outputs(self, data, eval_factory=None):
        """Evaluate EVERY output head in a single pass over the data
        (fixes the reference-parity gap where only output[0] was
        scored). Returns ``{output_name: Evaluation}``."""
        if eval_factory is None:
            from deeplearning4j_tpu.evaluation.classification import (
                Evaluation)
            eval_factory = Evaluation
        evs = [eval_factory() for _ in self.conf.network_outputs]
        for mds, preds in self._iter_pred_batches(data):
            for i, ev in enumerate(evs):
                lmask = (mds.labels_masks[i]
                         if mds.labels_masks is not None else None)
                self._eval_one(ev, mds.labels[i], np.asarray(preds[i]),
                               lmask)
        return dict(zip(self.conf.network_outputs, evs))

    def evaluate_regression(self, data, output_index: int = 0):
        from deeplearning4j_tpu.evaluation.regression import (
            RegressionEvaluation)
        return self._eval_with(data, RegressionEvaluation(), output_index)

    def evaluate_roc(self, data, threshold_steps: int = 0,
                     output_index: int = 0):
        from deeplearning4j_tpu.evaluation.roc import ROC
        return self._eval_with(data, ROC(threshold_steps), output_index)

    # ------------------------------------------------------------------
    def rnn_time_step(self, *inputs):
        """Stateful streaming inference (reference rnnTimeStep :2358)."""
        xs = [jnp.asarray(x) for x in inputs]
        squeeze = xs[0].ndim == 2
        if squeeze:
            xs = [x[:, None, :] for x in xs]
        if self._rnn_state is None:
            self._rnn_state = {}
        acts = dict(zip(self.conf.network_inputs, xs))
        for name in self.conf.topological_order():
            obj, ins = self.conf.vertices[name]
            xin = [acts[i] for i in ins]
            if isinstance(obj, BaseRecurrentLayer):
                carry = self._rnn_state.get(name)
                if carry is None:
                    carry = obj.zero_state(xin[0].shape[0])
                y, carry = obj.apply_rnn(self.params[name], xin[0], carry,
                                         training=False)
                self._rnn_state[name] = carry
                acts[name] = y
            elif hasattr(obj, "apply_stream"):
                # attention vertices: the streaming carry is the KV
                # cache (rnnTimeStep contract extended to transformers)
                acts[name], self._rnn_state[name] = obj.apply_stream(
                    self.params[name], self._rnn_state.get(name),
                    xin[0])
            elif isinstance(obj, Layer):
                acts[name], _ = obj.apply(self.params[name],
                                          self.state[name], xin[0],
                                          training=False)
            else:
                acts[name] = obj.apply(xin)
        outs = tuple(acts[o] for o in self.conf.network_outputs)
        if squeeze:
            outs = tuple(o[:, -1, :] if o.ndim == 3 else o for o in outs)
        return outs if len(outs) > 1 else outs[0]

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    def streaming_session(self, capacity: int, batch: int,
                          dtype=None):
        """Jitted bounded-cache streaming inference over the graph
        topology — the TPU-first counterpart to the eager
        ``rnn_time_step`` (see models/streaming.py)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.streaming import (
            GraphStreamingSession)
        return GraphStreamingSession(self, capacity, batch,
                                     dtype or jnp.float32)

    # ------------------------------------------------------------------
    # layerwise pretraining (reference ComputationGraph.pretrain
    # :652,664: each pretrainable layer vertex is trained on its own
    # input activations, fed through the already-pretrained stack)
    # ------------------------------------------------------------------
    def pretrain(self, data, *, epochs: int = 1):
        if self.params is None:
            self.init()
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        elif not isinstance(data, (list, tuple)):
            data = list(data)
        for name in self.conf.topological_order():
            obj, _ = self.conf.vertices[name]
            if isinstance(obj, Layer) and hasattr(obj, "pretrain_loss"):
                self._pretrain_vertex(name, data, epochs)
        return self

    def _pretrain_vertex(self, name: str, data, epochs: int):
        obj, ins = self.conf.vertices[name]
        opt = updaters_mod.to_optax(
            getattr(obj, "updater", None) or self.conf.conf.updater_cfg
            or updaters_mod.sgd())
        opt_state = opt.init(self.params[name])

        @jax.jit
        def pre_step(lp, opt_state, x, rng):
            def loss_fn(p):
                return obj.pretrain_loss(p, x, rng)

            loss, grads = jax.value_and_grad(loss_fn)(lp)
            updates, opt_state2 = opt.update(grads, opt_state, lp)
            return optax.apply_updates(lp, updates), opt_state2, loss

        # only the ancestor subgraph of the vertex's input is needed —
        # running the full DAG per batch would multiply pretraining
        # cost by the network depth
        needed = set()
        stack = [ins[0]]
        while stack:
            cur = stack.pop()
            if cur in needed or cur not in self.conf.vertices:
                continue
            needed.add(cur)
            stack.extend(self.conf.vertices[cur][1])

        @jax.jit
        def vertex_input(params, state, inputs, fmasks):
            acts, _, _ = self._forward(params, state, inputs,
                                       training=False, rng=None,
                                       fmasks=fmasks, only=needed)
            return acts[ins[0]]

        step = 0
        loss = float("nan")
        for _ in range(epochs):
            for ds in data:
                mds = self._as_multi(ds)
                inputs = tuple(jnp.asarray(f) for f in mds.features)
                fmasks = (tuple(None if m is None else jnp.asarray(m)
                                for m in mds.features_masks)
                          if mds.features_masks is not None else None)
                x = vertex_input(self.params, self.state, inputs, fmasks)
                rng = jax.random.fold_in(self._rng_key, step)
                self.params[name], opt_state, loss = pre_step(
                    self.params[name], opt_state, x, rng)
                step += 1
        logger.info("pretrained vertex '%s' (%s), final loss %.5f", name,
                    type(obj).__name__, float(loss))

    # ------------------------------------------------------------------
    # params plumbing (parity with MultiLayerNetwork; reference keeps a
    # flat params view per graph, ComputationGraph.params())
    # ------------------------------------------------------------------
    def num_params(self) -> int:
        return sum(int(p.size)
                   for p in jax.tree_util.tree_leaves(self.params))

    def params_flat(self) -> np.ndarray:
        from deeplearning4j_tpu.util.tree import tree_flat_vector
        return tree_flat_vector(self.params)

    def set_params_flat(self, flat: np.ndarray):
        from deeplearning4j_tpu.util.tree import tree_from_flat_vector
        self.params = tree_from_flat_vector(self.params, flat)

    def clone(self) -> "ComputationGraph":
        g = ComputationGraph(self.conf.clone())
        if self.params is not None:
            g.init()
            from deeplearning4j_tpu.util.tree import tree_copy
            g.params = tree_copy(self.params)
            g.state = tree_copy(self.state)
        return g

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def summary(self) -> str:
        lines = ["name                 type                      inputs"]
        for name in self.conf.topological_order():
            obj, ins = self.conf.vertices[name]
            lines.append(f"{name:<20} {type(obj).__name__:<25} {ins}")
        if self.params:
            lines.append(f"total params: {self.num_params()}")
        return "\n".join(lines)
