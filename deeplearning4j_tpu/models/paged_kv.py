"""Paged (block) KV cache: allocator, prefix cache, slot session.

The dense ``SlotStreamingSession`` reserves ``capacity`` cache rows
per slot up front, so slot count is bounded by ``slots x capacity``
KV memory whether or not the streams use it — the shape-bucket
ceiling the ROADMAP "decode fast path" item names. This module is the
vLLM-style paged memory model over the same layer math:

- **PagedKVAllocator** — one physical pool of fixed-size pages per
  model (per attention layer: a ``(n_pages, page_size, H, Dh)``
  buffer, allocated once). Pages are refcounted; a request reserves
  only the pages its ``prompt + n_tokens`` worst case needs, so
  concurrent slot count is bounded by TOTAL KV memory, not by
  per-slot capacity. Exhaustion is a typed admission error
  (``KVPagePoolExhaustedError``, HTTP 429 + ``Retry-After``), never
  an OOM mid-decode: reservation is up-front.
- **PrefixCache** — prompt-prefix reuse across requests: when a
  stream completes, the pages FULLY covered by its prompt become
  immutable and are registered under the rolling hash chain of the
  prompt's page-aligned prefixes. A later request whose prompt starts
  with a cached prefix points its page table at the shared pages
  (refcounted) and resumes prefill AFTER them — repeated-prompt
  traffic skips prefill. Shared pages are read-only; the one write
  a resumed stream must make inside a shared page (re-feeding the
  last prompt token when the whole prompt was covered) triggers
  copy-on-write. Cache entries are LRU-evicted when the allocator
  runs dry.
- **PagedSlotSession** — the continuous-batching substrate over page
  tables: one jitted (slots, 1) decode step; each attention layer
  writes new k/v into the slot's current page and attends over the
  slot's GATHERED virtual cache (``apply_stream_paged``). With
  ``pages_per_slot * page_size`` equal to the dense capacity the
  math is position-for-position identical to the dense path —
  greedy-token parity is tested.

Page id 0 is a reserved scratch page: inactive slots' page-table rows
are all-zero, so their dummy writes land in scratch and can never
corrupt a live page. The allocator hands out ids ``1..n_pages``.
"""

from __future__ import annotations

import json
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.serving.errors import (KVLeaseCorruptError,
                                               KVLeaseVersionError,
                                               KVPagePoolExhaustedError)

__all__ = ["PagedKVAllocator", "PrefixCache", "PagedSlotSession",
           "prefix_fingerprint", "prefix_fingerprints", "parse_lease",
           "LEASE_WIRE_VERSION"]


def _pages_for(tokens: int, page_size: int) -> int:
    return -(-int(tokens) // int(page_size))


# ---------------------------------------------------------------------------
# prefix fingerprints — the router-side half of KV-aware routing
# ---------------------------------------------------------------------------

def _prefix_bytes(tokens, n_tokens: Optional[int] = None) -> bytes:
    arr = np.asarray(tokens).reshape(-1)
    if n_tokens is not None:
        arr = arr[:int(n_tokens)]
    return np.ascontiguousarray(arr, dtype=np.int64).tobytes()


def prefix_fingerprint(tokens, n_tokens: Optional[int] = None) -> str:
    """8-hex digest of a page-aligned token prefix — the SAME bytes
    :class:`PrefixCache` keys on, so a fingerprint computed by the
    fleet router from a request's prompt matches the one a replica
    advertises for its cached entry. A routing hint, not an identity
    check: a (1-in-4-billion) collision merely routes to a replica
    without the prefix, which then prefills cold."""
    return format(zlib.crc32(_prefix_bytes(tokens, n_tokens))
                  & 0xFFFFFFFF, "08x")


def prefix_fingerprints(tokens, page_size: int) -> List[Tuple[int, str]]:
    """``[(n_tokens, fingerprint)]`` for every page-aligned prefix of
    the prompt, LONGEST FIRST — the probe order for "which replica
    holds my longest cached prefix". Runs on the router's routing
    hot path, so the digests are computed in ONE pass with a running
    crc32 (a from-scratch hash per prefix would make routing
    O(prompt² / page_size))."""
    tokens = np.asarray(tokens).reshape(-1)
    ps = int(page_size)
    data = _prefix_bytes(tokens)
    stride = ps * 8                    # int64 bytes per page
    crc = 0
    out = []
    for n in range(1, tokens.size // ps + 1):
        crc = zlib.crc32(data[(n - 1) * stride:n * stride], crc)
        out.append((n * ps, format(crc & 0xFFFFFFFF, "08x")))
    out.reverse()
    return out


# ---------------------------------------------------------------------------
# lease wire format
# ---------------------------------------------------------------------------

_LEASE_MAGIC = b"DKVL"
LEASE_WIRE_VERSION = 1


def parse_lease(blob: bytes) -> Tuple[dict, bytes]:
    """Split and validate a serialized lease: ``(header, payload)``.
    Bad magic / truncation / CRC mismatch raise
    :class:`KVLeaseCorruptError`; an unknown wire version raises
    :class:`KVLeaseVersionError`. Schema-vs-session compatibility is
    the importing session's job (:meth:`PagedSlotSession
    .import_lease`) — this function needs no model."""
    if not isinstance(blob, (bytes, bytearray, memoryview)):
        raise KVLeaseCorruptError(
            f"lease blob must be bytes, got {type(blob).__name__}")
    blob = bytes(blob)
    if len(blob) < len(_LEASE_MAGIC) + 8 \
            or blob[:len(_LEASE_MAGIC)] != _LEASE_MAGIC:
        raise KVLeaseCorruptError(
            "not a KV lease blob (bad magic or truncated header)")
    frame, tail = blob[:-4], blob[-4:]
    (frame_crc,) = struct.unpack("<I", tail)
    computed = zlib.crc32(frame) & 0xFFFFFFFF
    if computed != frame_crc:
        raise KVLeaseCorruptError(
            f"lease frame CRC mismatch (stored {frame_crc}, "
            f"computed {computed}) — the blob was corrupted in "
            "transit")
    (hdr_len,) = struct.unpack_from("<I", frame, len(_LEASE_MAGIC))
    start = len(_LEASE_MAGIC) + 4
    if len(frame) < start + hdr_len:
        raise KVLeaseCorruptError("lease header truncated")
    try:
        header = json.loads(frame[start:start + hdr_len].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise KVLeaseCorruptError(
            f"lease header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise KVLeaseCorruptError("lease header is not an object")
    version = header.get("version")
    if version != LEASE_WIRE_VERSION:
        raise KVLeaseVersionError(
            f"lease wire version {version!r} != supported "
            f"{LEASE_WIRE_VERSION}")
    payload = frame[start + hdr_len:]
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != header.get("payload_crc"):
        raise KVLeaseCorruptError(
            f"lease payload CRC mismatch (stored "
            f"{header.get('payload_crc')!r}, computed {crc}) — the "
            "blob was corrupted in transit")
    return header, payload


class PagedKVAllocator:
    """Refcounted free-list allocator over page ids ``1..n_pages``
    (id 0 is the session's scratch page). Thread-safe: admission
    checks read counts from request threads while the batcher worker
    allocates/frees."""

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        if page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are re-used first
        # (their pool rows are warm)
        self._free: List[int] = list(range(self.n_pages, 0, -1))
        self._ref = np.zeros(self.n_pages + 1, np.int32)

    # ---- queries ----
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def in_use(self) -> int:
        return self.n_pages - self.free_count()

    def refcount(self, page: int) -> int:
        with self._lock:
            return int(self._ref[page])

    # ---- alloc / refcount ----
    def alloc(self, n: int, evictor=None) -> List[int]:
        """Allocate ``n`` pages (refcount 1 each). When the free list
        is short and an ``evictor`` is given, it is asked to release
        ``needed`` pages (the prefix cache drops LRU entries there);
        still short afterwards raises
        :class:`KVPagePoolExhaustedError` with a backoff hint scaled
        to the shortfall — allocation is all-or-nothing."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        with self._lock:
            short = n - len(self._free)
        if short > 0 and evictor is not None:
            evictor.evict(short)
        with self._lock:
            if n > len(self._free):
                raise KVPagePoolExhaustedError(
                    f"KV page pool exhausted: {n} pages needed, "
                    f"{len(self._free)} free of {self.n_pages} — "
                    "active decodes free pages as they finish",
                    retry_after_s=max(0.1, 0.02 * n))
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
            return pages

    def incref(self, pages) -> None:
        with self._lock:
            for p in pages:
                if self._ref[p] <= 0:
                    raise ValueError(
                        f"incref on free page {p} (use-after-free)")
                self._ref[p] += 1

    def decref(self, pages) -> None:
        """Drop one reference per page; a page at refcount 0 returns
        to the free list."""
        with self._lock:
            for p in pages:
                if self._ref[p] <= 0:
                    raise ValueError(
                        f"decref on free page {p} (double free)")
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._free.append(p)

    def reset(self) -> None:
        """Forget everything (worker-restart recovery: the pool
        buffers were rebuilt, so every outstanding reference is
        dead)."""
        with self._lock:
            self._free = list(range(self.n_pages, 0, -1))
            self._ref[:] = 0


class PrefixCache:
    """Page-granular prompt-prefix index with LRU eviction.

    Keys are the page-aligned token prefixes themselves (exact match,
    not a lossy hash): a registered prompt of ``m`` full pages adds
    one entry per prefix length ``1..m``, so a later prompt sharing
    only the first page still hits. Each entry owns one refcount on
    each of its pages; eviction (LRU, driven by the allocator running
    dry) drops entries and their references — a page frees only when
    no entry AND no live slot references it."""

    def __init__(self, allocator: PagedKVAllocator):
        self._alloc = allocator
        self._entries: "OrderedDict[bytes, List[int]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits_total = 0
        self.evictions_total = 0

    @staticmethod
    def _key(tokens: np.ndarray, n_tokens: int) -> bytes:
        return np.ascontiguousarray(
            tokens[:n_tokens], dtype=np.int64).tobytes()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def register(self, tokens, pages: List[int]) -> int:
        """Register the chain of full-prompt pages ``pages`` (page i
        holds tokens ``[i*ps, (i+1)*ps)``). Returns how many new
        entries were added."""
        ps = self._alloc.page_size
        tokens = np.asarray(tokens).reshape(-1)
        added = 0
        with self._lock:
            for n in range(1, len(pages) + 1):
                key = self._key(tokens, n * ps)
                if key in self._entries:
                    self._entries.move_to_end(key)
                    continue
                chain = list(pages[:n])
                self._alloc.incref(chain)
                self._entries[key] = chain
                added += 1
        return added

    def lookup(self, tokens) -> List[int]:
        """Longest cached page chain matching the prompt's page-
        aligned prefix. The returned pages carry one NEW reference
        each (the caller's — release with ``decref``); empty list on
        miss. Counts a hit only when at least one page matched."""
        ps = self._alloc.page_size
        tokens = np.asarray(tokens).reshape(-1)
        with self._lock:
            for n in range(len(tokens) // ps, 0, -1):
                key = self._key(tokens, n * ps)
                chain = self._entries.get(key)
                if chain is not None:
                    self._entries.move_to_end(key)
                    self._alloc.incref(chain)
                    self.hits_total += 1
                    return list(chain)
        return []

    def evict(self, n_pages_needed: int) -> None:
        """Drop LRU entries until ~``n_pages_needed`` page references
        were released (or the cache is empty). Called by the
        allocator mid-``alloc``; pages shared with live slots lose
        the cache's reference but stay resident."""
        released = 0
        with self._lock:
            while self._entries and released < n_pages_needed:
                _, chain = self._entries.popitem(last=False)
                self._alloc.decref(chain)
                released += len(chain)
                self.evictions_total += 1

    def clear(self) -> None:
        with self._lock:
            for chain in self._entries.values():
                self._alloc.decref(chain)
            self._entries.clear()

    def fingerprints(self, limit: int = 512) -> List[str]:
        """Digests of the (up to ``limit``) most-recently-used
        cached prefixes — the per-replica advertisement the fleet
        router's prober scrapes for KV-aware routing. Entry keys ARE
        the page-aligned token-prefix bytes, so hashing them here
        matches :func:`prefix_fingerprint` over the same tokens."""
        with self._lock:
            keys = list(self._entries.keys())
        keys = keys[-int(limit):]
        return [format(zlib.crc32(k) & 0xFFFFFFFF, "08x")
                for k in keys]


class _Lease:
    """One admitted stream's page reservation."""

    __slots__ = ("pages", "resume_pos", "prefix_hit_tokens",
                 "prompt_len")

    def __init__(self, pages, resume_pos, prefix_hit_tokens,
                 prompt_len):
        self.pages = pages                    # table order
        self.resume_pos = resume_pos          # first position to feed
        self.prefix_hit_tokens = prefix_hit_tokens
        self.prompt_len = prompt_len


class PagedSlotSession:
    """Continuous-batching decode over a paged KV pool: the drop-in
    sibling of :class:`~deeplearning4j_tpu.models.streaming.
    SlotStreamingSession` whose per-slot state is a page table into
    one shared pool instead of a private ``capacity``-row cache.

    ``capacity`` still bounds ONE request's prompt+generation length
    (it is the page-table width in tokens); memory is bounded by
    ``n_pages * page_size`` total. Supported layers: paged attention
    (``apply_stream_paged``) and stateless layers — recurrent
    carries (``zero_state``) and running statistics have no paged
    analog; build the dense session for those models.
    """

    @staticmethod
    def supports(net) -> bool:
        """Can this model decode over page tables? False when any
        layer carries state with no paged analog (recurrent carry or
        running statistic) — the predicate the batcher's
        ``kv_mode="auto"`` fallback keys on, so that REAL
        construction errors (bad page_size/n_pages) are never
        mistaken for an unsupported model."""
        return not any(
            not hasattr(layer, "apply_stream_paged")
            and (hasattr(layer, "zero_state")
                 or hasattr(layer, "apply_stream"))
            for layer in net.layers)

    def __init__(self, net, slots: int, capacity: int,
                 page_size: int = 16, n_pages: Optional[int] = None,
                 dtype=None):
        import jax.numpy as jnp
        for i, layer in enumerate(net.layers):
            if hasattr(layer, "apply_stream_paged"):
                continue
            if hasattr(layer, "zero_state") or hasattr(
                    layer, "apply_stream"):
                raise ValueError(
                    f"layer {i} ({type(layer).__name__}) carries "
                    "state with no paged analog (recurrent carry or "
                    "running statistic); use the dense "
                    "SlotStreamingSession for this model")
        self.net = net
        self.slots = int(slots)
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        self.pages_per_slot = _pages_for(capacity, page_size)
        if n_pages is None:
            # memory parity with the dense session by default: the
            # win then comes from reserving per-request actual need
            n_pages = self.slots * self.pages_per_slot
        self._dtype = dtype or jnp.float32
        self.allocator = PagedKVAllocator(n_pages, self.page_size)
        self.prefix_cache = PrefixCache(self.allocator)
        self.slot_pos = np.zeros((self.slots,), np.int32)
        self._table = np.zeros((self.slots, self.pages_per_slot),
                               np.int32)
        self._leases: Dict[int, _Lease] = {}
        self._pools = self._fresh_pools()
        self._step = None
        self._copy_page = None

    # ---- pools ----
    def _fresh_pools(self):
        pools = []
        for layer in self.net.layers:
            if hasattr(layer, "apply_stream_paged"):
                # +1 physical row: page id 0 is the scratch page
                pools.append(layer.zero_page_pool(
                    self.allocator.n_pages + 1, self.page_size,
                    self._dtype))
            else:
                pools.append(None)
        return pools

    def pages_total(self) -> int:
        return self.allocator.n_pages

    def pages_in_use(self) -> int:
        return self.allocator.in_use()

    def slot_pages(self, slot: int) -> int:
        lease = self._leases.get(slot)
        return len(lease.pages) if lease is not None else 0

    def slot_prefix_hit(self, slot: int) -> int:
        lease = self._leases.get(slot)
        return lease.prefix_hit_tokens if lease is not None else 0

    # ---- admission-side API (batcher worker thread) ----
    def can_ever_fit(self, prompt_len: int, n_tokens: int) -> bool:
        """Could this request EVER be admitted (table width and whole
        pool permitting)? False means a client error, not transient
        pressure."""
        total = int(prompt_len) + int(n_tokens)
        return (total <= self.capacity
                and _pages_for(total, self.page_size)
                <= self.allocator.n_pages)

    def reserve(self, prompt, n_tokens: int) -> _Lease:
        """Reserve pages for one stream's ``prompt + n_tokens`` worst
        case, reusing cached prefix pages when the prompt matches.
        Raises :class:`KVPagePoolExhaustedError` (all-or-nothing)
        under transient pressure. The lease is not visible to the
        device until :meth:`bind`."""
        prompt = np.asarray(prompt).reshape(-1)
        T0 = prompt.size
        if T0 < 1:
            raise ValueError("prompt must contain at least one token")
        if T0 + int(n_tokens) > self.capacity:
            raise ValueError(
                f"prompt ({T0}) + n_tokens ({n_tokens}) exceeds the "
                f"page-table width (capacity {self.capacity})")
        total_pages = _pages_for(T0 + int(n_tokens), self.page_size)
        shared = self.prefix_cache.lookup(prompt)
        # the LAST prompt token must be re-fed to produce the first
        # output logits, so a hit can cover at most T0 - 1 positions
        resume = min(len(shared) * self.page_size, T0 - 1)
        cow_idx = resume // self.page_size
        need_cow = cow_idx < len(shared)
        fresh_needed = total_pages - len(shared) + (
            1 if need_cow else 0)
        try:
            fresh = self.allocator.alloc(fresh_needed,
                                         evictor=self.prefix_cache)
        except KVPagePoolExhaustedError:
            if shared:
                self.allocator.decref(shared)
            raise
        if need_cow:
            # the resume position sits INSIDE a shared page (whole
            # prompt was covered): copy-on-write it so the re-fed
            # token's write cannot touch the shared original
            cow_page = fresh.pop()
            self._device_copy_page(cow_page, shared[cow_idx])
            self.allocator.decref([shared[cow_idx]])
            shared = shared[:cow_idx] + [cow_page]
        pages = shared + fresh
        return _Lease(pages, resume,
                      prefix_hit_tokens=resume, prompt_len=T0)

    def bind(self, slot: int, lease: _Lease) -> None:
        self._table[slot, :] = 0
        self._table[slot, :len(lease.pages)] = lease.pages
        self.slot_pos[slot] = lease.resume_pos
        self._leases[slot] = lease

    def release(self, slot: int, register_prompt=None) -> None:
        """Recycle a slot: drop its page references; when the stream
        completed cleanly, first register its full-prompt pages in
        the prefix cache (the cache takes its own references)."""
        lease = self._leases.pop(slot, None)
        self._table[slot, :] = 0
        self.slot_pos[slot] = 0
        if lease is None:
            return
        if register_prompt is not None:
            prompt = np.asarray(register_prompt).reshape(-1)
            n_full = prompt.size // self.page_size
            if n_full > 0:
                self.prefix_cache.register(prompt,
                                           lease.pages[:n_full])
        self.allocator.decref(lease.pages)

    def release_all(self) -> None:
        for slot in list(self._leases):
            self.release(slot)

    def register_written_prefix(self, slot: int, prompt) -> int:
        """Donate the slot's FULLY-WRITTEN prompt pages to the
        prefix cache without releasing the lease — the prefill-
        export path's registration, where only ``slot_pos``
        positions (all but the last prompt token) are in the cache
        and the boundary page may be half-written. Returns how many
        pages were registered."""
        lease = self._leases.get(slot)
        if lease is None:
            return 0
        pos = int(self.slot_pos[slot])
        prompt = np.asarray(prompt).reshape(-1)
        n_full = min(pos, prompt.size) // self.page_size
        if n_full > 0:
            self.prefix_cache.register(prompt, lease.pages[:n_full])
        return n_full

    # ---- lease serialization: the prefill→decode / drain-migration
    #      wire format. A slot's attention state is its page-table
    #      pages' contents plus its position; everything else about
    #      the stream (prompt, sampled tokens, rng) is the CALLER's
    #      ``extra`` dict, carried opaquely in the header ----
    def _pool_schema(self) -> List[Optional[List[dict]]]:
        """Per-layer leaf schema (page-row shape + dtype) — what two
        replicas must agree on for a lease to be portable. None for
        stateless layers."""
        import jax
        schema: List[Optional[List[dict]]] = []
        for pool in self._pools:
            if pool is None:
                schema.append(None)
                continue
            leaves = jax.tree_util.tree_leaves(pool)
            schema.append([{"shape": list(leaf.shape[1:]),
                            "dtype": str(leaf.dtype)}
                           for leaf in leaves])
        return schema

    def export_lease(self, slot: int,
                     extra: Optional[dict] = None) -> bytes:
        """Serialize slot ``slot``'s attention state: a versioned
        header (wire version, page size, position, per-layer pool
        schema, the caller's ``extra``) followed by the raw contents
        of every page the stream has written, CRC-tagged. The slot
        and its lease are left untouched — the caller decides
        whether the incumbent keeps decoding (failed handoff) or
        releases (acked migration). Device→host gather happens here,
        one fixed-shape fetch per (layer leaf, page)."""
        import jax
        lease = self._leases.get(slot)
        if lease is None:
            raise ValueError(f"slot {slot} holds no lease to export")
        pos = int(self.slot_pos[slot])
        # only pages with WRITTEN positions travel: [0, pos)
        pages_written = _pages_for(pos, self.page_size) if pos else 0
        page_ids = lease.pages[:pages_written]
        chunks: List[bytes] = []
        for pool in self._pools:
            if pool is None:
                continue
            for leaf in jax.tree_util.tree_leaves(pool):
                for pid in page_ids:
                    chunks.append(np.ascontiguousarray(
                        np.asarray(leaf[pid])).tobytes())
        payload = b"".join(chunks)
        header = {
            "version": LEASE_WIRE_VERSION,
            "page_size": self.page_size,
            "pos": pos,
            "pages_written": pages_written,
            "layers": self._pool_schema(),
            "payload_crc": zlib.crc32(payload) & 0xFFFFFFFF,
            "extra": dict(extra or {}),
        }
        hdr = json.dumps(header).encode()
        frame = (_LEASE_MAGIC + struct.pack("<I", len(hdr)) + hdr
                 + payload)
        # trailing frame CRC over EVERYTHING (header included): the
        # payload CRC alone would let a bit flip inside the header —
        # pos, rng state, an emitted token — import silently-wrong
        # stream state instead of failing typed
        return frame + struct.pack("<I", zlib.crc32(frame)
                                   & 0xFFFFFFFF)

    def import_lease(self, blob: bytes,
                     total_tokens: int) -> Tuple[_Lease, dict]:
        """Rebuild an exported lease into THIS session's pool:
        validate the blob (magic/CRC → :class:`KVLeaseCorruptError`;
        wire version / page size / pool schema skew →
        :class:`KVLeaseVersionError`), reserve ``total_tokens``'
        worth of fresh pages (all-or-nothing, prefix cache evicted
        under pressure exactly like :meth:`reserve`), and scatter the
        payload pages into the physical pools — the rebuilt
        attention state is bit-identical to the exporter's (same
        bytes at the same in-page positions; everything past ``pos``
        is masked). Returns ``(lease, extra)``; bind the lease like
        any reservation."""
        import jax
        header, payload = parse_lease(blob)
        # every header field a crafted/corrupt blob controls is
        # validated TYPED here: this runs on the batcher worker
        # thread, and an untyped KeyError/IndexError would crash the
        # whole decode loop instead of failing one request
        try:
            page_size = int(header["page_size"])
            pos = int(header["pos"])
            pages_written = int(header["pages_written"])
            layers = header["layers"]
        except (KeyError, TypeError, ValueError) as e:
            raise KVLeaseCorruptError(
                f"lease header field missing or malformed: "
                f"{e!r}") from e
        if page_size != self.page_size:
            raise KVLeaseVersionError(
                f"lease page_size {page_size} != this "
                f"session's {self.page_size}")
        schema = self._pool_schema()
        if layers != schema:
            raise KVLeaseVersionError(
                "lease pool schema does not match this model's "
                "attention layers (different model or dtype)")
        if pos < 0 or pages_written != _pages_for(pos,
                                                  self.page_size):
            raise KVLeaseCorruptError(
                f"lease header inconsistent: pos {pos} does not "
                f"need {pages_written} page(s) of {self.page_size} "
                "tokens")
        if pos > int(total_tokens):
            raise KVLeaseCorruptError(
                f"lease position {pos} exceeds the request's token "
                f"budget {total_tokens}")
        total_pages = _pages_for(total_tokens, self.page_size)
        fresh = self.allocator.alloc(total_pages,
                                     evictor=self.prefix_cache)
        try:
            import jax.numpy as jnp
            n_leaf_rows = sum(len(s) for s in schema
                              if s is not None)
            row_bytes = [np.dtype(d["dtype"]).itemsize
                         * int(np.prod(d["shape"]))
                         for s in schema if s is not None
                         for d in s]
            expect = sum(b * pages_written for b in row_bytes)
            if len(payload) != expect:
                raise KVLeaseCorruptError(
                    f"lease payload is {len(payload)} bytes; schema "
                    f"demands {expect} ({n_leaf_rows} pool leaves x "
                    f"{pages_written} pages)")
            off = 0
            for i, pool in enumerate(self._pools):
                if pool is None:
                    continue
                leaves, treedef = jax.tree_util.tree_flatten(pool)
                new_leaves = []
                for leaf, spec in zip(leaves, schema[i]):
                    shape = tuple(spec["shape"])
                    dtype = np.dtype(spec["dtype"])
                    nb = dtype.itemsize * int(np.prod(shape))
                    for k in range(pages_written):
                        page = np.frombuffer(
                            payload, dtype=dtype, count=nb
                            // dtype.itemsize, offset=off
                        ).reshape(shape)
                        off += nb
                        leaf = leaf.at[fresh[k]].set(
                            jnp.asarray(page))
                    new_leaves.append(leaf)
                self._pools[i] = jax.tree_util.tree_unflatten(
                    treedef, new_leaves)
        except BaseException:
            self.allocator.decref(fresh)
            raise
        lease = _Lease(fresh, pos, prefix_hit_tokens=0,
                       prompt_len=pos)
        return lease, dict(header.get("extra") or {})

    # ---- device step ----
    def _device_copy_page(self, dst: int, src: int) -> None:
        import jax
        if self._copy_page is None:
            def copy(pool, dst, src):
                row = jax.tree_util.tree_map(lambda b: b[src], pool)
                return jax.tree_util.tree_map(
                    lambda b, r: b.at[dst].set(r), pool, row)

            self._copy_page = jax.jit(copy, donate_argnums=(0,))
        import jax.numpy as jnp
        d, s = jnp.int32(dst), jnp.int32(src)
        for i, pool in enumerate(self._pools):
            if pool is not None:
                self._pools[i] = self._copy_page(pool, d, s)

    def _make_step(self):
        import jax
        net = self.net
        layers = list(net.layers)
        preprocessors = dict(net.conf.preprocessors)

        def step(params, layer_states, pools, table, pos, x):
            h = x
            new_pools = list(pools)
            for i, layer in enumerate(layers):
                if i in preprocessors:
                    h = preprocessors[i](h)
                if hasattr(layer, "apply_stream_paged"):
                    h, new_pools[i] = layer.apply_stream_paged(
                        params[i], pools[i], table, pos, h)
                else:
                    h, _ = layer.apply(params[i], layer_states[i], h,
                                       training=False)
            return h, new_pools

        return jax.jit(step, donate_argnums=(2,))

    def step_slots(self, x, active):
        """One decode step for every slot at once — the
        ``SlotStreamingSession.step_slots`` contract: ``x`` is
        (slots, 1, C), free slots carry a dummy row (their write
        lands in the scratch page and their ``pos`` stays put).
        Returns the (slots, 1, V) output for the new step."""
        import jax.numpy as jnp
        x = jnp.asarray(x)
        active = np.asarray(active, bool)
        if x.shape[0] != self.slots:
            raise ValueError(f"x has {x.shape[0]} rows; session has "
                             f"{self.slots} slots")
        if active.any() and int(self.slot_pos[active].max()) >= \
                self.capacity:
            raise ValueError(
                f"slot overflow: an active slot is at pos "
                f"{int(self.slot_pos[active].max())} with capacity "
                f"{self.capacity} — admit shorter requests or build "
                "the session with a larger capacity")
        if self._step is None:
            self._step = self._make_step()
        # inactive slots step with pos 0 over their all-zero table
        # row: the write targets scratch, never a live page
        pos = np.where(active, self.slot_pos, 0).astype(np.int32)
        h, self._pools = self._step(
            self.net.params, self.net.state, self._pools,
            jnp.asarray(self._table), jnp.asarray(pos), x)
        self.slot_pos = self.slot_pos + active.astype(
            self.slot_pos.dtype)
        return h

    def reinit_states(self) -> None:
        """Post-crash recovery: the jitted step donates the pools, so
        after a failed step the buffers may be deleted device arrays.
        Rebuild them AND forget every page reference — the prefix
        cache's entries point at contents that no longer exist, so it
        must flush (its counters survive for the metrics)."""
        self._leases.clear()
        self.prefix_cache.clear()
        self.allocator.reset()
        self.slot_pos = np.zeros((self.slots,), np.int32)
        self._table = np.zeros((self.slots, self.pages_per_slot),
                               np.int32)
        self._pools = self._fresh_pools()
