"""k-step fused on-device training + AOT train-program warmup.

BENCH_DETAIL's MFU analysis shows small models are dispatch-bound:
LeNet spends ~1 ms/step in device compute but pays a full host
round-trip per step, with ±20% jitter. The classic fix is the
in-graph training loop of the TensorFlow papers (arXiv:1605.08695
§3.3, arXiv:1603.04467): keep the device busy across many steps per
host interaction, and pre-compile the executables so the steady state
never traces.

Two pieces, shared by both executors
(``models/multi_layer_network.py``, ``models/computation_graph.py``;
the executor supplies its traced single-step core ``_train_core`` and
this module supplies the window plumbing):

- :func:`make_kstep_fn` fuses k training steps into ONE device
  program — a ``lax.scan`` over a host-stacked ``[k, ...]`` batch
  window with the ``(params, state, opt_state)`` carry donated,
  emitting
  stacked per-step ``loss`` — and, when the health monitor is
  attached, the fused ``[k, 5]`` health block — so the host still
  observes EVERY step from a single device→host fetch per window:
  detection/rollback lag is bounded by k, never lost. k is a
  PYTHON-static loop bound (the scan length is the window's leading
  dim, fixed at trace time), never a traced value — no GL002
  recompile hazard.

- :func:`aot_compile` / :func:`warmup_train_programs` pre-build the
  k-step program AND the k=1 tail-remainder program via
  ``jit(...).lower(shapes).compile()`` at startup — compilation from
  abstract shapes only, no execution (training warmup must not
  advance params) and no real buffers. The executors then dispatch
  the AOT-compiled executable directly whenever the incoming batch
  signature matches, so the steady state neither traces nor compiles
  (``observability.compile_watch.zero_compile_scope`` proves it).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Sequence, Tuple

import numpy as np

__all__ = ["signature", "stack_batches", "make_kstep_fn",
           "aot_compile", "warmup_train_programs", "canonical_np",
           "KStepExecutorMixin"]


def signature(tree) -> Tuple:
    """Hashable shape/dtype signature of an argument pytree.

    The treedef is part of the key, so mask-presence (a ``None`` slot
    vs an array) distinguishes signatures. Used both as the AOT
    program-cache key and as the uniformity check that decides
    whether a window of batches may be fused into one scan."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef,
            tuple((tuple(np.shape(l)), np.dtype(_dtype_of(l)).str)
                  for l in leaves))


def _dtype_of(x):
    dt = getattr(x, "dtype", None)
    return dt if dt is not None else np.asarray(x).dtype


def canonical_np(x):
    """Host array in JAX's CANONICAL dtype (f64→f32, i64→i32 unless
    x64 is enabled). The executors' host batch tuples go through
    this so an AOT cache key computed from host arrays matches what
    ``jnp.asarray`` will actually hand the program at dispatch — a
    float64 label array (``np.eye`` defaults to f64) must not make
    the warmed k=1 executable unreachable."""
    import jax
    a = np.asarray(x)
    dt = jax.dtypes.canonicalize_dtype(a.dtype)
    return a if a.dtype == dt else a.astype(dt)


def stack_batches(batch_tuples: Sequence):
    """Host-stack k same-signature batch tuples into one ``[k, ...]``
    window (``np.stack`` per leaf; ``None`` mask slots must be
    ``None`` in every batch — enforced upstream by comparing
    :func:`signature`). Stacking on HOST means the window reaches the
    device as one transfer and the per-batch device arrays of the
    per-step path are never materialized."""
    if len(batch_tuples) < 2:
        raise ValueError("a window needs at least 2 batches")
    import jax
    return jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *batch_tuples)


def make_kstep_fn(step_core, k: int, health_enabled: bool,
                  out_shardings=None):
    """Build the fused k-step train program.

    ``step_core(params, state, opt_state, batch, rng)`` is the
    executor's traced single-step math — the SAME function the k=1
    jitted step wraps, so the two programs compute identical updates
    (bit-identical params across k, regression-tested).

    Donation (GL003-audited): the ``(params, state, opt_state)``
    carry is consumed by the scan — argnums 0-2 donate and the caller
    rebinds from the outputs. The stacked window is deliberately NOT
    donated even though its buffer is dead after the call: scan xs
    are consumed by slicing and no output shares their shape, so XLA
    can never alias them — donation would be a no-op that warns
    "donated buffers were not usable" on every trace. ``base_rng`` is
    reused across calls and must not donate either.

    ``out_shardings`` (the mesh-spec fit path,
    ``parallel/mesh_spec.py``) pins the program's output layout to
    the input layout: without the pin GSPMD may emit a different
    sharding for a carry leaf than the one it arrived with, and the
    NEXT window's changed input shardings silently recompile every
    call.
    """
    if k < 2:
        raise ValueError("k-step fusion needs k >= 2; the k=1 path "
                         "is the executor's single-step program")
    import jax
    import jax.numpy as jnp

    jit_kwargs = {}
    if out_shardings is not None:
        jit_kwargs["out_shardings"] = out_shardings

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                       **jit_kwargs)
    def kstep_train(params, state, opt_state, window, base_rng, step0):
        def body(carry, xs):
            p, s, o = carry
            batch_i, i = xs
            # per-step rng identical to the per-step loop's
            # fold_in(base_rng, iteration_count): step0 + i
            rng = jax.random.fold_in(base_rng, step0 + i)
            out = step_core(p, s, o, batch_i, rng)
            if health_enabled:
                p2, s2, o2, loss, health = out
                return (p2, s2, o2), (loss, health)
            p2, s2, o2, loss = out
            return (p2, s2, o2), loss

        (p, s, o), ys = jax.lax.scan(
            body, (params, state, opt_state),
            (window, jnp.arange(k, dtype=jnp.int32)))
        if health_enabled:
            losses, healths = ys
            return p, s, o, losses, healths
        return p, s, o, ys

    return kstep_train


def aot_compile(jit_fn, example_args) -> Tuple[Any, float]:
    """``jit(...).lower(shapes).compile()``: build the executable from
    abstract shapes WITHOUT executing (a training warmup must not
    advance params) and WITHOUT allocating real buffers. Returns
    ``(compiled, seconds)``; the compiled object is directly callable
    with concrete arguments of exactly this signature (donation
    preserved).

    Example leaves that are mesh-placed ``jax.Array``s (or
    ``ShapeDtypeStruct``s already carrying a sharding — the
    mesh-spec fit path's abstract batches) keep their sharding in
    the lowered signature, so the compiled executable accepts
    exactly the sharded arguments dispatch will feed it; a
    sharding-less lowering would compile an executable the sharded
    steady state can never hit."""
    import jax
    from jax.sharding import NamedSharding

    def _abstract(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        return jax.ShapeDtypeStruct(np.shape(x), _dtype_of(x))

    abstract = jax.tree_util.tree_map(_abstract, example_args)
    t0 = time.perf_counter()
    compiled = jit_fn.lower(*abstract).compile()
    return compiled, time.perf_counter() - t0


class KStepExecutorMixin:
    """The executor-side window plumbing both executors share — one
    copy, so a fix to program selection, AOT dispatch, the per-step
    listener fan-out or the window entry point cannot drift between
    them. The host executor supplies ``_train_core``,
    ``_batch_tuple``/``_batch_tuple_np``, the
    ``_jit_train_step``/``_jit_kstep``/``_aot`` caches, and three
    small adapters — ``_coerce_fit_batch`` (DataSet → its native
    batch object), ``_batch_is_tbptt`` and ``_run_tbptt``; batches
    only need ``num_examples()``.

    MESH-SPEC SHARDING (``parallel/mesh_spec.py``): :meth:`use_mesh`
    installs a :class:`~deeplearning4j_tpu.parallel.mesh_spec.MeshContext`
    — params/opt-state placed per the spec (tensor-parallel rules
    over 'model', replication over 'data'), every batch/window
    transfer sharded over 'data', and every train program (k=1 AND
    the fused k-step scan) built with pinned ``out_shardings`` so
    the sharded steady state never recompiles. The k-step window
    machinery below is mesh-agnostic: a fused window over a dp x tp
    mesh is the same ``lax.scan`` program, GSPMD-partitioned —
    fused multichip steps in ONE device program."""

    # the installed MeshContext (None = single-device semantics);
    # a class default so both executors inherit it without touching
    # their __init__s
    _mesh_ctx = None

    def use_mesh(self, mesh_spec, devices=None, *,
                 respect_existing: bool = False):
        """Install a declarative mesh spec (``"dp=4,tp=2"`` | dict |
        JSON | a prebuilt ``MeshContext``) on this executor: place
        the model, and invalidate every compiled train program so
        the next fit builds sharded, output-pinned executables.
        ``respect_existing`` keeps param leaves a caller already
        placed on an equal mesh (the ParallelWrapper contract)."""
        from deeplearning4j_tpu.parallel.mesh_spec import (
            MeshContext, build_mesh_context)
        if mesh_spec is None:
            return self
        tbptt = self.conf.conf.tbptt
        if tbptt is not None:
            raise NotImplementedError(
                "tBPTT does not compose with mesh_spec yet (the "
                "chunked step threads recurrent carries the sharded "
                "program does not pin); drop tbptt or the mesh spec")
        if self.params is None:
            self.init()
        ctx = (mesh_spec if isinstance(mesh_spec, MeshContext)
               else build_mesh_context(mesh_spec, self, devices))
        cur = self._mesh_ctx
        if (cur is not None and cur.plan == ctx.plan
                and tuple(cur.mesh.devices.flat)
                == tuple(ctx.mesh.devices.flat)):
            # same spec over the same devices: keep the installed
            # context AND its compiled programs — warmup(mesh_spec=X)
            # followed by fit(mesh_spec=X) must not flush the
            # AOT-warmed executables and recompile on the first step
            cur.place_model(self, respect_existing=True)
            return self
        self._mesh_ctx = ctx
        ctx.place_model(self, respect_existing=respect_existing)
        # every compiled program pins shardings — rebuild them all
        self._flush_compiled_programs()
        return self

    def _flush_compiled_programs(self) -> None:
        """Drop every compiled/AOT train program — the ONE flush
        both mesh installers use (``use_mesh`` here, the wrapper's
        shrink/regrow rebuild), so a future executor cache cannot be
        missed at one site and serve stale-mesh executables."""
        self._jit_train_step = None
        self._jit_tbptt_step = None
        self._jit_kstep = {}
        self._aot = {}

    def _mesh_out_shardings(self):
        """Pinned ``out_shardings`` for the train programs under the
        installed mesh context (None otherwise) — the single place
        that knows how many trailing scalar/stacked outputs the step
        tuple carries (loss, plus the health block when enabled)."""
        if self._mesh_ctx is None:
            return None
        n_out = 2 if self._health_enabled else 1
        return self._mesh_ctx.step_out_shardings(self, n_out)

    def _train_jit_kwargs(self) -> dict:
        """Extra ``jax.jit`` kwargs for the executor's k=1 train
        step: pinned ``out_shardings`` under a mesh context (see
        module docstring), nothing otherwise."""
        sh = self._mesh_out_shardings()
        return {} if sh is None else {"out_shardings": sh}

    def _fit_epoch(self, data_iter, k: int, tbptt) -> None:
        """One epoch's batch loop (shared by both executors' ``fit``):
        time the data wait, collect k-batch windows (k > 1), flush on
        tBPTT entries so step order is preserved, and flush the tail
        at exhaustion. Epoch hooks stay with the caller."""
        from deeplearning4j_tpu.observability.tracing import trace
        pending = []          # k-step window under collection
        while True:
            # data wait timed apart from the step so the profiler/
            # tracer can tell an input-starved chip from a
            # dispatch-bound host
            t0 = time.perf_counter()
            with trace.span("data_wait"):
                ds = next(data_iter, None)
            if ds is None:
                break
            wait = time.perf_counter() - t0
            m = self._coerce_fit_batch(ds)
            if self._batch_is_tbptt(m, tbptt):
                # tBPTT chunks its own loop — flush the window first
                # so step order is preserved
                self._flush_window(pending, k)
                with trace.span("train_step_tbptt"):
                    self._run_tbptt(m, tbptt, data_wait_s=wait)
                continue
            if k == 1:
                self._fit_one(m, wait)
                continue
            pending.append((m, wait))
            if len(pending) == k:
                self._flush_window(pending, k)
        self._flush_window(pending, k)

    def _fit_one(self, ds, data_wait_s: float = 0.0):
        """One single-step device call + listener pass (the k=1 path,
        byte-for-byte the pre-k-step fit-loop body)."""
        from deeplearning4j_tpu.observability.tracing import trace
        t1 = time.perf_counter()
        with trace.span("train_step"):
            if self._mesh_ctx is not None:
                # shard from HOST arrays: host→mesh device_put is a
                # plain per-shard copy, while resharding an already-
                # committed device array onto a multi-axis mesh
                # compiles a _multi_slice program per shape — a stray
                # compile the warmed zero-compile steady state must
                # not pay
                batch = self._mesh_ctx.shard_batch(
                    self._batch_tuple_np(ds))
            else:
                batch = self._batch_tuple(ds)
            out = self._step_fn_for(batch)(
                self.params, self.state, self.opt_state, batch,
                self._rng_key, np.int32(self.iteration_count))
        if self._health_enabled:
            (self.params, self.state, self.opt_state,
             loss, self._last_health) = out
        else:
            (self.params, self.state, self.opt_state, loss) = out
        self._last_batch = batch
        self.score_value = loss
        # (data_wait_s, dispatch_s) — ProfilerListener
        self._step_timing = (data_wait_s, time.perf_counter() - t1)
        with trace.span("listeners"):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count, loss,
                                   ds.num_examples())
        self.iteration_count += 1

    def fit_batches(self, batches, *, steps_per_device_call=1):
        """Train on a list of batches in one listener-visible pass
        with NO epoch bookkeeping (ElasticTrainer's window entry
        point, the k-step analog of ``ParallelWrapper.fit_batch``).
        When ``len(batches) == steps_per_device_call > 1`` and all
        batches share one shape signature, the whole window runs as
        a single fused device program; otherwise batches run through
        the (pre-compiled) single-step program. The default is the
        per-step path — fusing is OPT-IN via ``steps_per_device_call``
        because a fused program's compile cost grows with k (a
        convenience caller passing 200 batches must not silently
        compile a 200-step scan). Returns the per-step losses as a
        host numpy array."""
        from deeplearning4j_tpu.observability.tracing import trace
        if self.params is None:
            self.init()
        self._sync_health_mode()
        if self._jit_train_step is None:
            self._jit_train_step = self._make_train_step()
        items = [self._coerce_fit_batch(d) for d in batches]
        k = int(steps_per_device_call)
        tbptt = self.conf.conf.tbptt
        if k > 1 and len(items) == k and not any(
                self._batch_is_tbptt(m, tbptt) for m in items):
            tups = [self._batch_tuple_np(m) for m in items]
            if len({signature(t) for t in tups}) == 1:
                return self._dispatch_window(tups, items, [0.0] * k, k)
        out = []
        for i, m in enumerate(items):
            # which window entry is live (a tBPTT entry spans several
            # iterations — ElasticTrainer must not map a mid-entry
            # rollback to a neighbouring batch's ordinal)
            self._window_batch_index = i
            if self._batch_is_tbptt(m, tbptt):
                with trace.span("train_step_tbptt"):
                    self._run_tbptt(m, tbptt)
                out.append(float(self.score_value))
                continue
            self._fit_one(m)
            out.append(float(self.score_value))
        return np.asarray(out, dtype=np.float64)

    def _step_fn_for(self, batch):
        """The k=1 program for this batch signature: the AOT-compiled
        executable when :meth:`warmup` built one (zero trace, zero
        compile), else the jit wrapper."""
        if self._aot:
            fn = self._aot.get(("train1", signature(batch)))
            if fn is not None:
                return fn
        return self._jit_train_step

    def _kstep_fn_for(self, window, k: int):
        if self._aot:
            fn = self._aot.get(("kstep", k, signature(window)))
            if fn is not None:
                return fn
        fn = self._jit_kstep.get(k)
        if fn is None:
            fn = self._jit_kstep[k] = make_kstep_fn(
                self._train_core, k, self._health_enabled,
                out_shardings=self._mesh_out_shardings())
        return fn

    def _flush_window(self, pending, k: int):
        """Dispatch the collected window: one fused program when the
        window is FULL (len == k) and every batch shares one shape
        signature; anything else (the epoch tail, a shape-churn
        batch) runs per-batch through the pre-compiled k=1 program —
        never a fresh mid-epoch trace of an odd-length scan."""
        if not pending:
            return
        batches = [d for d, _ in pending]
        waits = [w for _, w in pending]
        del pending[:]
        if len(batches) == k and k > 1:
            tups = [self._batch_tuple_np(d) for d in batches]
            if len({signature(t) for t in tups}) == 1:
                self._dispatch_window(tups, batches, waits, k)
                return
        for d, w in zip(batches, waits):
            self._fit_one(d, w)

    def _dispatch_window(self, tups, batches, waits, k: int):
        """One fused k-step device call, then the per-step listener
        pass over the stacked outputs. The loss vector (and, with a
        health listener, the [k, 5] health block) is fetched ONCE per
        window — every step is still observed, detection lag is
        bounded by k."""
        from deeplearning4j_tpu.observability.tracing import trace
        window = stack_batches(tups)
        if self._mesh_ctx is not None:
            window = self._mesh_ctx.shard_window(window)
        fn = self._kstep_fn_for(window, k)
        t1 = time.perf_counter()
        with trace.span("train_step_fused"):
            out = fn(self.params, self.state, self.opt_state, window,
                     self._rng_key, np.int32(self.iteration_count))
        health_host = None
        if self._health_enabled:
            (self.params, self.state, self.opt_state,
             losses, healths) = out
            health_host = np.asarray(healths)     # ONE fetch, [k, 5]
        else:
            (self.params, self.state, self.opt_state, losses) = out
        loss_host = np.asarray(losses)            # ONE fetch, [k]
        dispatch_s = time.perf_counter() - t1
        # the last sub-batch (host arrays — the stacked window's
        # device buffer was consumed by the scan) for the
        # dead-activation checker
        self._last_batch = tups[-1]
        per_step_s = dispatch_s / k
        with trace.span("listeners"):
            for i in range(k):
                # which window entry is live — ElasticTrainer maps a
                # listener-raised rollback back to its batch ordinal
                # through this (robust to multi-iteration tBPTT
                # entries on the non-fused path)
                self._window_batch_index = i
                self._last_health = (None if health_host is None
                                     else health_host[i])
                self.score_value = loss_host[i]
                self._step_timing = (waits[i], per_step_s)
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count,
                                       loss_host[i],
                                       batches[i].num_examples())
                self.iteration_count += 1
        return loss_host


def warmup_train_programs(model, batch_np, k: int) -> Dict[str, float]:
    """AOT-compile a model's train-step programs for one batch
    signature: the k=1 single-step program (also the tail-remainder
    program when ``n_batches % k != 0``) and, for ``k > 1``, the
    fused k-step scan program. Installs the executables in
    ``model._aot`` (keyed by signature, consulted by the fit loop
    before falling back to the jit wrapper) and returns
    ``{program_name: compile_seconds}`` for what was actually built
    (already-warm signatures are skipped).

    Works on both executors — needs ``_train_core`` /
    ``_jit_train_step`` / ``_jit_kstep`` / ``_aot`` /
    ``_health_enabled`` and live ``params/state/opt_state/_rng_key``
    (call after ``init()``; the executor's ``warmup()`` method
    handles that)."""
    out: Dict[str, float] = {}
    # under a mesh context the lowered batch/window signatures carry
    # the data shardings dispatch will use — a sharding-less lowering
    # would build executables the sharded fit loop can never hit
    ctx = getattr(model, "_mesh_ctx", None)
    batch_ex = ctx.abstract_batch(batch_np) if ctx else batch_np
    args1 = (model.params, model.state, model.opt_state, batch_ex,
             model._rng_key, np.int32(0))
    key1 = ("train1", signature(batch_np))
    if key1 not in model._aot:
        compiled, secs = aot_compile(model._jit_train_step, args1)
        model._aot[key1] = compiled
        out["train_step"] = secs
    if k > 1:
        window = stack_batches([batch_np] * k)
        keyk = ("kstep", k, signature(window))
        if keyk not in model._aot:
            # the SAME get-or-create the fit loop uses — warmup and
            # dispatch can never build different programs for one k
            fn = model._kstep_fn_for(window, k)
            window_ex = ctx.abstract_window(window) if ctx else window
            argsk = (model.params, model.state, model.opt_state,
                     window_ex, model._rng_key, np.int32(0))
            compiled, secs = aot_compile(fn, argsk)
            model._aot[keyk] = compiled
            out[f"kstep_{k}"] = secs
    return out
