"""MultiLayerNetwork: the sequential-stack executor.

TPU rewrite of nn/multilayer/MultiLayerNetwork.java (3186 LoC). The
reference's per-iteration machinery — feedForwardToLayer (:900),
backprop (:1278)/calcBackpropGradients (:1293) with per-layer manual
gradients, Solver/StochasticGradientDescent (:57-100), updater blocks,
workspaces — collapses into ONE jitted ``train_step``:

    loss(params) = output_layer.loss(forward(params, x)) + reg
    grads        = jax.grad(loss)          (replaces calcBackpropGradients)
    updates      = optax update            (replaces UpdaterBlock.update)
    params'      = params + updates        (replaces StepFunction.step)
    constraints  = projection              (replaces applyConstraints :96)

XLA fuses the whole thing into a single TPU program; buffers are
donated so params update in place in HBM (the workspace analog).

Masking, tBPTT (doTruncatedBPTT :1404), stateful streaming inference
(rnnTimeStep :2656), layerwise pretraining (:221-343), and listener
dispatch (:1180, :89) all have direct equivalents below.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    ArrayDataSetIterator, DataSetIterator, ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import updaters as updaters_mod
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.layers.output import (
    CenterLossOutputLayer, OutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.recurrent import BaseRecurrentLayer
from deeplearning4j_tpu.models.kstep import KStepExecutorMixin
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.train.constraints import apply_layer_constraints

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["MultiLayerNetwork"]


def _as_iterator(data, labels=None, batch_size=None) -> DataSetIterator:
    if isinstance(data, DataSetIterator):
        return data
    if isinstance(data, DataSet):
        if batch_size is None:
            return ListDataSetIterator([data])
        return ListDataSetIterator(data.batch_by(batch_size))
    if labels is not None:
        return ArrayDataSetIterator(data, labels,
                                    batch_size or data.shape[0])
    raise TypeError(f"Cannot build iterator from {type(data)}")


class MultiLayerNetwork(KStepExecutorMixin):
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self.params: Optional[List[Dict[str, jnp.ndarray]]] = None
        self.state: Optional[List[Dict[str, jnp.ndarray]]] = None
        self.opt_state = None
        self.listeners = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value: float = float("nan")
        self._rng_key = None
        self._rnn_state: Optional[List[Any]] = None    # rnnTimeStep stateMap
        self._jit_train_step = None
        self._jit_tbptt_step = None
        # k-step fused programs (models/kstep.py): dict k -> jitted
        # scan program, plus AOT-compiled executables keyed by batch
        # signature (warmup() fills; the fit loop dispatches them
        # directly so the steady state never traces or compiles)
        self._jit_kstep: Dict[int, Any] = {}
        self._aot: Dict[tuple, Any] = {}
        self._jit_output = {}
        self._optimizer = None
        # (data_wait_s, dispatch_s) of the latest fit iteration —
        # read by observability.step_profile.ProfilerListener
        self._step_timing = None
        # observability.health wiring: when a listener sets
        # wants_device_health, the train step also returns the fused
        # [finite_bits, loss, |grads|, |updates|, |params|] vector,
        # stashed here UNFETCHED (the monitor does the one transfer)
        self._health_enabled = False
        self._last_health = None
        # device refs of the latest batch tuple (for the monitor's
        # optional dead-activation forward pass) — a reference, not a
        # copy or sync
        self._last_batch = None

    # ------------------------------------------------------------------
    # init (reference MultiLayerNetwork.init :396-554)
    # ------------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        seed = self.conf.conf.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        self._rng_key = jax.random.fold_in(key, 0xD1)
        params, states = [], []
        t = self.conf.input_type
        keys = jax.random.split(key, max(len(self.layers), 1))
        for i, layer in enumerate(self.layers):
            if t is not None and i in self.conf.preprocessors:
                t = self.conf.preprocessors[i].output_type(t)
            if t is not None:
                layer.set_n_in(t)
            p, s = layer.initialize(keys[i], t)
            params.append(p)
            states.append(s)
            if t is not None:
                t = layer.output_type(t)
        self.params = params
        self.state = states
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        global_cfg = self.conf.conf.updater_cfg or updaters_mod.sgd()
        overrides = [getattr(l, "updater", None) for l in self.layers]
        if any(o is not None for o in overrides):
            labels = []
            transforms = {"__global__": updaters_mod.to_optax(global_cfg)}
            for i, (l, o) in enumerate(zip(self.layers, overrides)):
                if o is not None:
                    name = f"layer{i}"
                    transforms[name] = updaters_mod.to_optax(o)
                else:
                    name = "__global__"
                labels.append(jax.tree_util.tree_map(lambda _: name,
                                                     self.params[i]))
            self._optimizer = optax.multi_transform(transforms, labels)
        else:
            self._optimizer = updaters_mod.to_optax(global_cfg)
        clip = self.conf.conf.gradient_clip
        if clip is not None:
            if clip["type"] == "norm":
                pre = optax.clip_by_global_norm(clip["v"])
            elif clip["type"] == "value":
                pre = optax.clip(clip["v"])
            else:
                raise ValueError(clip)
            self._optimizer = optax.chain(pre, self._optimizer)
        self.opt_state = self._optimizer.init(self.params)
        self._jit_train_step = None    # invalidate
        self._jit_tbptt_step = None
        self._jit_kstep = {}
        self._aot = {}

    # ------------------------------------------------------------------
    # forward (reference feedForward :863-975)
    # ------------------------------------------------------------------
    def _forward(self, params, state, x, *, training, rng, fmask=None,
                 upto: Optional[int] = None, collect=False, carries=None):
        """carries: optional per-layer recurrent (h, c) initial states —
        used by tBPTT to carry hidden state across chunks (reference
        rnnActivateUsingStoredState :2219). Returns new carries too."""
        acts = []
        new_states = []
        new_carries = [None] * len(self.layers)
        n = len(self.layers) if upto is None else upto
        for i in range(len(self.layers)):
            layer = self.layers[i]
            if i >= n:
                new_states.append(state[i])
                continue
            from deeplearning4j_tpu.nn.errors import layer_error_context
            if i in self.conf.preprocessors:
                with layer_error_context(f"preprocessor before layer {i}",
                                         self.conf.preprocessors[i], x):
                    x = self.conf.preprocessors[i](x)
            lrng = None
            if rng is not None:
                lrng = jax.random.fold_in(rng, i)
            with layer_error_context(f"layer {i}", layer, x):
                if carries is not None and isinstance(layer,
                                                     BaseRecurrentLayer):
                    c0 = carries[i]
                    if c0 is None:
                        c0 = layer.zero_state(x.shape[0])
                    xd = layer.apply_input_dropout(x, training=training,
                                                   rng=lrng)
                    x, c1 = layer.apply_rnn(params[i], xd, c0,
                                            training=training,
                                            rng=lrng, mask=fmask)
                    new_carries[i] = c1
                    s = state[i]
                else:
                    x, s = layer.apply(params[i], state[i], x,
                                       training=training,
                                       rng=lrng, mask=fmask)
            new_states.append(s)
            if collect:
                acts.append(x)
        return x, new_states, acts, new_carries

    def _loss(self, params, state, batch, rng, *, training=True,
              carries=None):
        x, labels, fmask, lmask = batch
        out_idx = len(self.layers) - 1
        out_layer = self.layers[out_idx]
        if not out_layer.has_loss():
            raise ValueError("Last layer has no loss; use an OutputLayer/"
                             "LossLayer for fit()")
        h, new_states, _, new_carries = self._forward(
            params, state, x, training=training, rng=rng, fmask=fmask,
            upto=out_idx, carries=carries)
        if out_idx in self.conf.preprocessors:
            h = self.conf.preprocessors[out_idx](h)
        orng = jax.random.fold_in(rng, out_idx) if rng is not None else None
        loss = out_layer.loss_from_input(params[out_idx], h, labels,
                                         training=training, rng=orng,
                                         mask=lmask)
        if isinstance(out_layer, CenterLossOutputLayer):
            loss = loss + out_layer.lambda_ * out_layer.center_loss(
                state[out_idx], h, labels)
            new_states[out_idx] = out_layer.update_centers(
                state[out_idx], h, labels)
        reg = jnp.zeros(())
        for layer, p in zip(self.layers, params):
            reg = reg + layer.regularization_loss(p)
        if carries is not None:
            return loss + reg, (new_states, new_carries)
        return loss + reg, new_states

    # ------------------------------------------------------------------
    # jitted train step (replaces Solver.optimize + SGD.optimize)
    # ------------------------------------------------------------------
    def _train_core(self, params, state, opt_state, batch, rng):
        """Traced single-step training math: loss → grads → updates →
        constraints (+ the fused health vector when a health listener
        is attached). Shared verbatim by the k=1 jitted step and the
        k-step ``lax.scan`` body (models/kstep.py), so the fused and
        per-step programs compute bit-identical updates."""
        from deeplearning4j_tpu.train.gradnorm import (
            apply_gradient_normalization)
        optimizer = self._optimizer

        def loss_fn(p):
            loss, new_states = self._loss(p, state, batch, rng,
                                          training=True)
            return loss, new_states

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = apply_gradient_normalization(self.layers, grads)
        updates, new_opt_state = optimizer.update(grads, opt_state,
                                                  params)
        new_params = optax.apply_updates(params, updates)
        new_params = [
            apply_layer_constraints(l, p)
            for l, p in zip(self.layers, new_params)
        ]
        if self._health_enabled:
            # fused finite check + global norms, computed inside
            # this same XLA program (observability/health.py)
            from deeplearning4j_tpu.observability.health import (
                fused_health)
            health = fused_health(loss, grads, updates, new_params)
            return new_params, new_states, new_opt_state, loss, health
        return new_params, new_states, new_opt_state, loss

    def _make_train_step(self):
        core = self._train_core

        # under a mesh context the program's output layout is pinned
        # to the placed model's (kstep._train_jit_kwargs) — GSPMD
        # must not drift a carry sharding and recompile every step
        @functools.partial(jax.jit, donate_argnums=(0, 1, 2),
                           **self._train_jit_kwargs())
        def train_step(params, state, opt_state, batch, base_rng, step):
            # step arrives as a traced scalar; folding inside the jit
            # avoids a host-side dispatch per iteration
            rng = jax.random.fold_in(base_rng, step)
            return core(params, state, opt_state, batch, rng)

        return train_step

    def _sync_health_mode(self) -> None:
        """Compile the fused health check into the train step iff a
        health-monitoring listener is attached (one jit invalidation
        per toggle, not per fit)."""
        want = any(getattr(l, "wants_device_health", False)
                   for l in self.listeners)
        if want != self._health_enabled:
            self._health_enabled = want
            self._jit_train_step = None
            self._jit_tbptt_step = None
            # the k-step programs' output structure includes the
            # stacked health block iff enabled — rebuild them too
            self._jit_kstep = {}
            self._aot = {}
            if not want:
                self._last_health = None

    def _make_tbptt_step(self):
        """Train step that also threads recurrent carries across chunks
        (reference doTruncatedBPTT :1404: state carried, gradient
        truncated at chunk boundaries)."""
        optimizer = self._optimizer
        from deeplearning4j_tpu.train.gradnorm import (
            apply_gradient_normalization)

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def tbptt_step(params, state, opt_state, batch, carries, base_rng,
                       step):
            rng = jax.random.fold_in(base_rng, step)
            carries = jax.lax.stop_gradient(carries)

            def loss_fn(p):
                loss, aux = self._loss(p, state, batch, rng, training=True,
                                       carries=carries)
                return loss, aux

            (loss, (new_states, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = apply_gradient_normalization(self.layers, grads)
            updates, new_opt_state = optimizer.update(grads, opt_state,
                                                      params)
            new_params = optax.apply_updates(params, updates)
            new_params = [apply_layer_constraints(l, p)
                          for l, p in zip(self.layers, new_params)]
            return (new_params, new_states, new_opt_state, loss,
                    jax.lax.stop_gradient(new_carries))

        return tbptt_step

    def _batch_tuple(self, ds: DataSet):
        f = jnp.asarray(ds.features)
        l = None if ds.labels is None else jnp.asarray(ds.labels)
        fm = None if ds.features_mask is None else jnp.asarray(
            ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        return (f, l, fm, lm)

    def _batch_tuple_np(self, ds: DataSet):
        """Host-side batch tuple (numpy, no device transfer, dtypes
        JAX-canonicalized): the unit the k-step window stacker works
        on — stacking k batches on host means ONE host→device
        transfer per window instead of k, and canonical dtypes keep
        AOT cache keys consistent with what the program actually
        receives."""
        from deeplearning4j_tpu.models.kstep import canonical_np
        f = canonical_np(ds.features)
        l = None if ds.labels is None else canonical_np(ds.labels)
        fm = None if ds.features_mask is None else canonical_np(
            ds.features_mask)
        lm = (None if ds.labels_mask is None
              else canonical_np(ds.labels_mask))
        return (f, l, fm, lm)

    # ------------------------------------------------------------------
    # fit (reference fit(DataSetIterator) :1167)
    # ------------------------------------------------------------------
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: Optional[int] = None,
            steps_per_device_call: int = 1, mesh_spec=None):
        """``steps_per_device_call=k`` fuses k train steps into ONE
        device program (a ``lax.scan`` over a stacked batch window —
        models/kstep.py): the dispatch-bound regime pays one host
        round-trip per k steps instead of per step. Listeners still
        fire per step (losses and the fused health vector come back
        stacked, one fetch per window); a tail of ``n_batches % k``
        runs through the k=1 program — pre-compile both with
        :meth:`warmup` and the steady state never compiles.

        ``mesh_spec`` ("dp=4,tp=2" | dict | JSON — see
        ``parallel/mesh_spec.py``) trains SHARDED: params placed per
        the spec, batches split over the mesh's data axis, and the
        train programs (fused k-step windows included) run as single
        SPMD device programs with pinned output shardings. Composes
        with ``steps_per_device_call`` — k sharded steps per host
        round-trip."""
        from deeplearning4j_tpu.observability.tracing import trace
        k = int(steps_per_device_call)
        if k < 1:
            raise ValueError("steps_per_device_call must be >= 1")
        if mesh_spec is not None:
            self.use_mesh(mesh_spec)
        if self.params is None:
            self.init()
        it = _as_iterator(data, labels, batch_size)
        self._sync_health_mode()
        if self._jit_train_step is None:
            self._jit_train_step = self._make_train_step()
        tbptt = self.conf.conf.tbptt
        try:
            for _ in range(epochs):
                with trace.span("epoch"):
                    for lst in self.listeners:
                        lst.on_epoch_start(self)
                    self._fit_epoch(iter(it), k, tbptt)
                    for lst in self.listeners:
                        lst.on_epoch_end(self)
                self.epoch_count += 1
        except Exception as e:
            # black box: an escaping exception leaves a post-mortem
            # bundle when a flight recorder is installed (no-op
            # otherwise), then propagates unchanged
            from deeplearning4j_tpu.observability.flight_recorder \
                import on_fit_exception
            on_fit_exception(self, e)
            raise
        return self

    # KStepExecutorMixin adapters (fit_batches/_fit_one live there)
    def _coerce_fit_batch(self, ds: DataSet) -> DataSet:
        return ds

    def _batch_is_tbptt(self, ds: DataSet, tbptt) -> bool:
        return tbptt is not None and ds.features.ndim == 3

    def _run_tbptt(self, ds: DataSet, tbptt,
                   data_wait_s: float = 0.0) -> None:
        self._fit_tbptt(ds, None, tbptt, data_wait_s=data_wait_s)

    def warmup(self, example: DataSet, *,
               steps_per_device_call: int = 1, mesh_spec=None):
        """AOT warmup: ``jit(...).lower(shapes).compile()`` the train
        programs this batch signature will need — the k-step fused
        program (``steps_per_device_call > 1``) and the k=1
        single-step/tail-remainder program — so a subsequent
        ``fit``/``fit_batches`` steady state compiles ZERO times
        (``compile_watch.zero_compile_scope`` can assert it). Attach
        listeners (HealthMonitor in particular) BEFORE warming: the
        health toggle changes the program signature and flushes the
        AOT cache. Only the example's signature is warmed — a shape
        not seen here (e.g. a partial final batch when the dataset
        size isn't divisible by the batch size) still compiles once
        on first use; warm it with a second ``warmup`` call, or rely
        on the persistent cache (``--xla-cache``) to make it
        one-time across runs. Returns
        ``{program: compile_seconds}``."""
        from deeplearning4j_tpu.models import kstep as _kstep
        if mesh_spec is not None:
            self.use_mesh(mesh_spec)
        if self.params is None:
            self.init()
        self._sync_health_mode()
        if self._jit_train_step is None:
            self._jit_train_step = self._make_train_step()
        batch_np = self._batch_tuple_np(example)
        return _kstep.warmup_train_programs(
            self, batch_np, int(steps_per_device_call))

    def _fit_tbptt(self, ds: DataSet, step_fn_unused, tbptt,
                   data_wait_s: float = 0.0):
        """Truncated BPTT (reference doTruncatedBPTT :1404): split the
        sequence into fwd_length chunks; recurrent hidden state carries
        across chunks (stop_gradient at the boundary), exactly the
        reference's carried-state/truncated-gradient semantics.
        ``data_wait_s`` is the batch's input wait, billed to the FIRST
        chunk's ``_step_timing`` (each chunk is one listener
        iteration; later chunks waited on no data)."""
        import time
        fwd = tbptt["fwd_length"]
        T = ds.features.shape[1]
        B = ds.features.shape[0]
        # the tBPTT step has no fused health vector: a stale one from
        # the standard path must not masquerade as this chunk's
        self._last_health = None
        if self._jit_tbptt_step is None:
            self._jit_tbptt_step = self._make_tbptt_step()
        step_fn = self._jit_tbptt_step
        carries = [layer.zero_state(B)
                   if isinstance(layer, BaseRecurrentLayer) else None
                   for layer in self.layers]
        for start in range(0, T, fwd):
            end = min(start + fwd, T)
            sub = DataSet(
                ds.features[:, start:end],
                None if ds.labels is None else ds.labels[:, start:end],
                None if ds.features_mask is None
                else ds.features_mask[:, start:end],
                None if ds.labels_mask is None
                else ds.labels_mask[:, start:end])
            t_chunk = time.perf_counter()
            batch = self._batch_tuple(sub)
            (self.params, self.state, self.opt_state, loss,
             carries) = step_fn(self.params, self.state, self.opt_state,
                                batch, carries, self._rng_key,
                                np.int32(self.iteration_count))
            self.score_value = loss
            self._step_timing = (data_wait_s if start == 0 else 0.0,
                                 time.perf_counter() - t_chunk)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count, loss,
                                   sub.num_examples())
            self.iteration_count += 1

    # ------------------------------------------------------------------
    # inference (reference output :1876-1971)
    # ------------------------------------------------------------------
    def output(self, x, training: bool = False):
        if self.params is None:
            self.init()
        x = jnp.asarray(x)
        if training not in self._jit_output:
            @jax.jit
            def fwd(params, state, x, rng):
                y, _, _, _ = self._forward(params, state, x,
                                           training=training, rng=rng)
                return y
            self._jit_output[training] = fwd
        rng = self._rng_key if training else None
        return self._jit_output[training](self.params, self.state, x, rng)

    def feed_forward(self, x, training: bool = False) -> List[jnp.ndarray]:
        """All layer activations (reference feedForward :863)."""
        x = jnp.asarray(x)
        rng = self._rng_key if training else None
        _, _, acts, _ = self._forward(self.params, self.state, x,
                                      training=training, rng=rng,
                                      collect=True)
        return acts

    def score(self, ds: DataSet, training: bool = False) -> float:
        batch = self._batch_tuple(ds)
        loss, _ = self._loss(self.params, self.state, batch,
                             self._rng_key if training else None,
                             training=training)
        return float(loss)

    def evaluate(self, data, labels=None):
        from deeplearning4j_tpu.evaluation.classification import Evaluation
        it = _as_iterator(data, labels)
        ev = Evaluation()
        for ds in it:
            preds = np.asarray(self.output(ds.features))
            ev.eval(ds.labels, preds, mask=ds.labels_mask)
        return ev

    def evaluate_regression(self, data, labels=None):
        from deeplearning4j_tpu.evaluation.regression import (
            RegressionEvaluation)
        it = _as_iterator(data, labels)
        ev = RegressionEvaluation()
        for ds in it:
            preds = np.asarray(self.output(ds.features))
            ev.eval(ds.labels, preds, mask=ds.labels_mask)
        return ev

    def evaluate_roc(self, data, labels=None, threshold_steps: int = 0):
        from deeplearning4j_tpu.evaluation.roc import ROC
        it = _as_iterator(data, labels)
        roc = ROC(threshold_steps)
        for ds in it:
            preds = np.asarray(self.output(ds.features))
            roc.eval(ds.labels, preds)
        return roc

    # ------------------------------------------------------------------
    # layerwise pretraining (reference pretrain :221-343)
    # ------------------------------------------------------------------
    def pretrain(self, data, *, epochs: int = 1, batch_size=None):
        if self.params is None:
            self.init()
        it = _as_iterator(data, None, batch_size)
        for idx, layer in enumerate(self.layers):
            if not hasattr(layer, "pretrain_loss"):
                continue
            self._pretrain_layer(idx, it, epochs)
        return self

    def _pretrain_layer(self, idx: int, it: DataSetIterator, epochs: int):
        layer = self.layers[idx]
        opt = updaters_mod.to_optax(
            getattr(layer, "updater", None) or self.conf.conf.updater_cfg)
        opt_state = opt.init(self.params[idx])

        @jax.jit
        def pre_step(lp, opt_state, x, rng):
            def loss_fn(p):
                return layer.pretrain_loss(p, x, rng)

            loss, grads = jax.value_and_grad(loss_fn)(lp)
            updates, opt_state2 = opt.update(grads, opt_state, lp)
            return optax.apply_updates(lp, updates), opt_state2, loss

        step = 0
        for _ in range(epochs):
            for ds in it:
                x = jnp.asarray(ds.features)
                # feed input forward through the already-pretrained stack
                if idx > 0:
                    x, _, _, _ = self._forward(self.params, self.state, x,
                                               training=False, rng=None,
                                               upto=idx)
                rng = jax.random.fold_in(self._rng_key, step)
                self.params[idx], opt_state, loss = pre_step(
                    self.params[idx], opt_state, x, rng)
                step += 1
        logger.info("pretrained layer %d (%s), final loss %.5f", idx,
                    type(layer).__name__, float(loss))

    # ------------------------------------------------------------------
    # stateful RNN inference (reference rnnTimeStep :2656)
    # ------------------------------------------------------------------
    def rnn_time_step(self, x):
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:                      # (B,C) -> single timestep
            x = x[:, None, :]
        if self._rnn_state is None:
            self._rnn_state = [None] * len(self.layers)
        h = x
        for i, layer in enumerate(self.layers):
            if i in self.conf.preprocessors:
                h = self.conf.preprocessors[i](h)
            if isinstance(layer, BaseRecurrentLayer):
                carry = self._rnn_state[i]
                if carry is None:
                    carry = layer.zero_state(h.shape[0])
                h, carry = layer.apply_rnn(self.params[i], h, carry,
                                           training=False)
                self._rnn_state[i] = carry
            elif hasattr(layer, "apply_stream"):
                # attention layers: the streaming carry is the KV
                # cache (rnnTimeStep contract extended to
                # transformers)
                h, self._rnn_state[i] = layer.apply_stream(
                    self.params[i], self._rnn_state[i], h)
            else:
                h, _ = layer.apply(self.params[i], self.state[i], h,
                                   training=False)
        if squeeze and h.ndim == 3:
            h = h[:, -1, :]
        return h

    def rnn_clear_previous_state(self):
        self._rnn_state = None

    def streaming_session(self, capacity: int, batch: int,
                          dtype=None):
        """Jitted bounded-cache streaming inference: the TPU-first
        counterpart to the eager ``rnn_time_step`` (same contract,
        one compiled XLA executable per chunk length, fixed-capacity
        KV caches updated in place — see models/streaming.py).
        ``capacity`` is the max total sequence length the session can
        stream before ``reset()``."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.streaming import StreamingSession
        return StreamingSession(self, capacity, batch,
                                dtype or jnp.float32)

    def slot_streaming_session(self, capacity: int, slots: int,
                               dtype=None):
        """Per-slot-position streaming session for continuous
        batching: each of the ``slots`` batch rows is an independent
        decode stream that can be reset and re-admitted while its
        neighbours keep generating (see
        ``serving.ContinuousBatcher``)."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.streaming import (
            SlotStreamingSession)
        return SlotStreamingSession(self, capacity, slots,
                                    dtype or jnp.float32)

    def paged_slot_streaming_session(self, capacity: int, slots: int,
                                     page_size: int = 16,
                                     n_pages=None, dtype=None):
        """Paged-KV continuous-batching session: per-slot page tables
        into one refcounted page pool, so concurrent slot count is
        bounded by total KV memory (``n_pages * page_size`` tokens)
        instead of ``slots x capacity`` — plus prompt-prefix sharing
        between slots (see ``models/paged_kv.py``). Raises
        ``ValueError`` for models whose layers carry state with no
        paged analog (recurrent carries, running statistics)."""
        from deeplearning4j_tpu.models.paged_kv import PagedSlotSession
        return PagedSlotSession(self, slots=slots, capacity=capacity,
                                page_size=page_size, n_pages=n_pages,
                                dtype=dtype)

    # ------------------------------------------------------------------
    # params plumbing (reference flat params view :542-554)
    # ------------------------------------------------------------------
    def num_params(self) -> int:
        return sum(int(p.size)
                   for p in jax.tree_util.tree_leaves(self.params))

    def params_flat(self) -> np.ndarray:
        from deeplearning4j_tpu.util.tree import tree_flat_vector
        return tree_flat_vector(self.params)

    def set_params_flat(self, flat: np.ndarray):
        from deeplearning4j_tpu.util.tree import tree_from_flat_vector
        self.params = tree_from_flat_vector(self.params, flat)

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def clone(self) -> "MultiLayerNetwork":
        m = MultiLayerNetwork(self.conf.clone())
        if self.params is not None:
            m.init()
            from deeplearning4j_tpu.util.tree import tree_copy
            m.params = tree_copy(self.params)
            m.state = tree_copy(self.state)
        return m

    def summary(self) -> str:
        lines = ["idx  type                      params    out_type"]
        t = self.conf.input_type
        for i, layer in enumerate(self.layers):
            if t is not None and i in self.conf.preprocessors:
                t = self.conf.preprocessors[i].output_type(t)
            n = (sum(int(p.size) for p in
                     jax.tree_util.tree_leaves(self.params[i]))
                 if self.params else 0)
            t = layer.output_type(t) if t is not None else None
            lines.append(f"{i:<4} {type(layer).__name__:<25} {n:<9} {t}")
        lines.append(f"total params: {self.num_params() if self.params else 0}")
        return "\n".join(lines)
