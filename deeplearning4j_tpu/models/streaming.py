"""Jitted bounded-cache streaming inference (rnnTimeStep, compiled).

``rnn_time_step`` on both executors (reference
MultiLayerNetwork.java:2656, ComputationGraph.java:2358) is
deliberately eager: it matches the reference contract, grows attention
KV caches by concat, and pays a Python dispatch per token-step — fine
for debugging, wrong as a TPU inference path (round-4 verdict weak #7:
O(T^2) total copy traffic).

The sessions here are the TPU-first variant: every stream carry has a
STATIC shape — attention layers get a fixed-capacity KV cache written
in place with ``lax.dynamic_update_slice`` (O(t) traffic per step),
recurrent layers carry their usual state — so one XLA executable per
chunk length covers the whole decode, with a single device dispatch
per step and no retrace as the sequence grows.

Chunk lengths are compile-time buckets: a session caches one
executable per distinct chunk length it sees (a decode loop uses
exactly one, t=1; a prompt prefill adds one more), plus one extra
trace when a running-statistic carry (GlobalPooling) materializes on
its first step (its feature width is unknown before data flows).
Keep chunk sizes consistent — every new length is a new compile.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["StreamingSession", "GraphStreamingSession",
           "SlotStreamingSession"]


class _BoundedSession:
    """Shared machinery of both executors' sessions: the
    chunk-length-keyed executable cache, position/capacity/batch
    bookkeeping, and device-side autoregressive generation."""

    def __init__(self, capacity: int, batch: int):
        self.capacity = int(capacity)
        self.batch = int(batch)
        self.pos = 0
        self._step_cache = {}
        # (n_tokens, greedy?) -> program. Temperature is a TRACED
        # operand of the fused program, never part of the key: a
        # float key would compile one executable per distinct
        # temperature, so per-request jitter (0.7 vs 0.7000001)
        # churns executables without bound (the GL002 recompile
        # hazard). Only the greedy/sampled STRUCTURE is static —
        # greedy has no RNG carry to thread.
        self._gen_cache = {}

    def _fn_for(self, t: int):
        fn = self._step_cache.get(t)
        if fn is None:
            fn = self._step_cache[t] = self._make_step(t)
        return fn

    def _raw_step(self, t: int):
        """The un-jitted step body for chunk length ``t`` — pure, so
        it can sit inside a larger jitted program (fused generate's
        lax.scan)."""
        raise NotImplementedError

    def _check(self, B: int, t: int) -> None:
        if B != self.batch:
            raise ValueError(f"batch {B} != session batch "
                             f"{self.batch}")
        if self.pos + t > self.capacity:
            raise ValueError(
                f"stream overflow: pos {self.pos} + chunk {t} exceeds "
                f"capacity {self.capacity} — create the session with "
                f"a larger capacity or reset()")

    def _make_step(self, t: int):
        raise NotImplementedError

    def _fused_ctx(self):
        """The fused program's ``feed``:
        ``(params, layer_states, states, pos, x) -> (h, states)``
        with x (B, 1, 1). Subclass hook, called only on a program
        CACHE MISS (building the raw step closure is not free)."""
        raise NotImplementedError

    def _model_params(self):
        """(params, layer_states) fetched fresh per call. Subclass
        hook."""
        raise NotImplementedError

    def _n_outputs(self) -> int:
        return 1

    @staticmethod
    def _sample_greedy(last):
        return jnp.argmax(last, axis=-1)

    @staticmethod
    def _sample_temp(last, temp, key):
        key, sub = jax.random.split(key)
        # output layers emit probabilities (softmax applied):
        # sample in log space. ``temp`` may be a traced scalar (the
        # fused program) or a python float (the unfused loop) — the
        # math is identical either way, which is what the fused/
        # unfused id-parity contract (tested) rests on.
        nxt = jax.random.categorical(
            sub, jnp.log(last + 1e-9) / temp, axis=-1)
        return nxt, key

    @staticmethod
    def _sample(last, temp, key):
        """(next_ids, new_key) for a CONCRETE temperature — the
        unfused loop's dispatcher over the two shared sampling
        bodies."""
        if temp > 0:
            return _BoundedSession._sample_temp(last, temp, key)
        return _BoundedSession._sample_greedy(last), key

    def generate(self, prompt, n_tokens: int, *,
                 temperature: float = 0.0, rng_key=None,
                 fused: bool = False):
        """Autoregressive generation for id-input (embedding-first)
        language models — single-input graphs and layer stacks alike:
        prefill the (B, T0) integer prompt as one chunk, then decode
        ``n_tokens`` greedily (temperature=0) or by temperature
        sampling. The sampling runs on DEVICE arrays — no per-token
        host sync; the only fetch is the caller's. Returns
        (B, n_tokens) generated ids.

        ``fused=True`` compiles the ENTIRE decode loop into one XLA
        program (lax.scan over the sampled tokens with the bounded
        caches as carries): a single device dispatch replaces
        n_tokens of them — the difference dominates when dispatch
        latency is high (e.g. a tunnel'd chip). One compile per
        (n_tokens, greedy-vs-sampled) — the temperature itself is a
        traced operand, so per-request temperature jitter reuses one
        executable; identical ids to the unfused path
        for the same rng_key (tested). Needs
        ``capacity >= T0 + n_tokens`` fused (the last sampled token
        is written to cache) vs ``T0 + n_tokens - 1`` unfused."""
        prompt = jnp.asarray(prompt)
        if prompt.ndim != 2:
            raise ValueError(
                f"prompt must be (B, T0) token ids; got shape "
                f"{prompt.shape}")
        if self._n_outputs() != 1:
            # checked BEFORE the prefill: failing after it would
            # leave the session's caches/pos silently advanced
            raise ValueError(
                "generate() needs a single-output network; this "
                "graph has multiple network_outputs")
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        if fused and self.pos + prompt.shape[1] + n_tokens > \
                self.capacity:
            raise ValueError(
                f"fused generate writes every sampled token: pos "
                f"{self.pos} + prompt {prompt.shape[1]} + n_tokens "
                f"{n_tokens} exceeds capacity {self.capacity}")
        # EmbeddingSequenceLayer reads (B, t, 1) id channels
        probs = self.step(prompt[:, :, None].astype(jnp.float32))
        last = probs[:, -1]
        temp = float(temperature)
        if fused:
            return self._generate_fused(last, n_tokens, temp,
                                        rng_key)
        out = []
        for i in range(n_tokens):
            nxt, rng_key = self._sample(last, temp, rng_key)
            out.append(nxt)
            if i + 1 < n_tokens:
                probs = self.step(
                    nxt[:, None, None].astype(jnp.float32))
                last = probs[:, 0]
        return jnp.stack(out, axis=1)

    def _generate_fused(self, last, n_tokens, temp, rng_key):
        params, lstates = self._model_params()
        greedy = temp <= 0
        prog = self._gen_cache.get((n_tokens, greedy))
        if prog is None:
            feed = self._fused_ctx()
            sample_greedy = self._sample_greedy
            sample_temp = self._sample_temp

            def program(params, lstates, states, pos, last, key,
                        temp):
                def body(carry, _):
                    states, pos, last, key = carry
                    if greedy:       # static: chosen at trace time
                        nxt = sample_greedy(last)
                    else:
                        nxt, key = sample_temp(last, temp, key)
                    x = nxt[:, None, None].astype(jnp.float32)
                    h, states = feed(params, lstates, states, pos, x)
                    return (states, pos + 1, h[:, 0], key), nxt

                (states, pos, _, _), ids = jax.lax.scan(
                    body, (states, pos, last, key), None,
                    length=n_tokens)
                return jnp.swapaxes(ids, 0, 1), states

            prog = self._gen_cache[(n_tokens, greedy)] = jax.jit(
                program, donate_argnums=(2,))
        ids, self._states = prog(params, lstates, self._states,
                                 jnp.int32(self.pos), last, rng_key,
                                 jnp.float32(temp))
        self.pos += n_tokens
        return ids


class StreamingSession(_BoundedSession):
    """Stateful token-streaming over a ``MultiLayerNetwork``.

    Built via ``net.streaming_session(capacity=...)``. ``step(x)``
    accepts (B, C) single steps or (B, t, C) chunks and returns the
    network output for the new steps only; feeding chunks
    sequentially equals one full-sequence forward (tested vs both the
    eager ``rnn_time_step`` and ``output``).
    """

    def __init__(self, net, capacity: int, batch: int,
                 dtype=jnp.float32):
        super().__init__(capacity, batch)
        self.net = net
        self._dtype = dtype
        self._states = self._fresh_states()

    def _fresh_states(self):
        states = []
        for layer in self.net.layers:
            if hasattr(layer, "apply_stream_bounded"):
                states.append(layer.zero_stream_cache(
                    self.batch, self.capacity, self._dtype))
            elif hasattr(layer, "zero_state"):
                states.append(layer.zero_state(self.batch))
            else:
                states.append(None)
        return states

    def _raw_step(self, t: int):
        net = self.net
        layers = list(net.layers)
        preprocessors = dict(net.conf.preprocessors)

        def step(params, layer_states, stream_states, pos, x):
            h = x
            new_streams = list(stream_states)
            for i, layer in enumerate(layers):
                if i in preprocessors:
                    h = preprocessors[i](h)
                if hasattr(layer, "apply_stream_bounded"):
                    h, new_streams[i] = layer.apply_stream_bounded(
                        params[i], stream_states[i], h, pos)
                elif hasattr(layer, "zero_state") and hasattr(
                        layer, "apply_rnn"):
                    h, new_streams[i] = layer.apply_rnn(
                        params[i], h, stream_states[i],
                        training=False)
                elif hasattr(layer, "apply_stream"):
                    # running-statistic carries (GlobalPooling's
                    # sum/count/max) — static shapes, jittable; a
                    # per-chunk apply() here would silently pool only
                    # the newest chunk
                    h, new_streams[i] = layer.apply_stream(
                        params[i], stream_states[i], h)
                else:
                    h, _ = layer.apply(params[i], layer_states[i], h,
                                       training=False)
            return h, new_streams

        return step

    def _make_step(self, t: int):
        # donated stream states: the KV caches genuinely update in
        # place (undonated inputs cannot alias outputs, which would
        # re-copy the full capacity each token-step)
        return jax.jit(self._raw_step(t), donate_argnums=(2,))

    def _fused_ctx(self):
        return self._raw_step(1)

    def _model_params(self):
        return self.net.params, self.net.state

    def step(self, x):
        """Feed the next chunk; returns outputs for the new steps.
        (B, C) input -> (B, C) output (single step, squeezed);
        (B, t, C) -> (B, t, C)."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        B, t, _ = x.shape
        self._check(B, t)
        h, self._states = self._fn_for(t)(
            self.net.params, self.net.state, self._states,
            jnp.int32(self.pos), x)
        self.pos += t
        if squeeze and h.ndim == 3:
            h = h[:, -1, :]
        return h

    def reset(self):
        """Start a new sequence: rewind the position. Attention
        caches need no zeroing (slots beyond ``pos`` are masked and
        overwritten); recurrent carries and running-pool statistics
        do."""
        self.pos = 0
        for i, layer in enumerate(self.net.layers):
            if hasattr(layer, "apply_stream_bounded"):
                continue
            if hasattr(layer, "zero_state"):
                self._states[i] = layer.zero_state(self.batch)
            elif hasattr(layer, "apply_stream"):
                self._states[i] = None     # running pool restarts


class SlotStreamingSession(StreamingSession):
    """Continuous-batching substrate: a StreamingSession whose ``pos``
    is PER SLOT (a (B,) vector), so each batch row is an independent
    decode stream that can be reset and re-admitted while its
    neighbours keep generating — the iteration-level scheduling the
    serving layer needs (admit new requests into free KV-cache slots
    between steps instead of draining the whole batch).

    Built on the scalar machinery by vmapping the t=1 raw step over
    the batch axis: every slot runs the exact B=1 computation with its
    own position, so a request's logits are bitwise independent of
    which other slots are occupied (slot-parity is tested). The KV
    mask (k_pos <= q_pos) makes slot reuse free for attention caches —
    a re-admitted slot starts at pos 0 and never sees the previous
    occupant's stale keys; recurrent carries DO need zeroing, which
    ``reset_slot`` does row-wise.

    Restriction: running-statistic carries (``apply_stream`` layers,
    e.g. GlobalPooling) lazily materialize state with restart-at-None
    semantics that has no per-row reset — such layers are rejected at
    construction (use the one-shot predict path for those models).
    """

    def __init__(self, net, capacity: int, slots: int,
                 dtype=jnp.float32):
        for i, layer in enumerate(net.layers):
            if (not hasattr(layer, "apply_stream_bounded")
                    and not hasattr(layer, "zero_state")
                    and hasattr(layer, "apply_stream")):
                raise ValueError(
                    f"layer {i} ({type(layer).__name__}) carries a "
                    "running statistic (apply_stream) with no per-"
                    "slot reset; SlotStreamingSession cannot host it")
        super().__init__(net, capacity, slots, dtype)
        self.slots = slots
        self.slot_pos = np.zeros((slots,), np.int32)
        self._slot_step = None

    def _make_slot_step(self):
        raw = self._raw_step(1)

        def per_slot(params, lstates, states, pos, x):
            # re-grow the batch axis the vmap stripped: the raw step
            # (and every layer under it) is written for (B, t, C)
            states1 = jax.tree_util.tree_map(lambda s: s[None], states)
            h, new_states = raw(params, lstates, states1, pos,
                                x[None])
            return h[0], jax.tree_util.tree_map(lambda s: s[0],
                                                new_states)

        vm = jax.vmap(per_slot, in_axes=(None, None, 0, 0, 0))
        return jax.jit(vm, donate_argnums=(2,))

    def step_slots(self, x, active):
        """One decode step for every slot at once. ``x`` is
        (slots, 1, C) — occupied slots carry their next token, free
        slots a dummy (their output is ignored and their ``pos`` does
        not advance, so the dummy write is overwritten on admission).
        ``active`` is a (slots,) bool mask. Returns the (slots, 1, V)
        network output for the new step."""
        x = jnp.asarray(x)
        active = np.asarray(active, bool)
        if x.shape[0] != self.slots:
            raise ValueError(f"x has {x.shape[0]} rows; session has "
                             f"{self.slots} slots")
        if active.any() and int(self.slot_pos[active].max()) >= \
                self.capacity:
            raise ValueError(
                f"slot overflow: an active slot is at pos "
                f"{int(self.slot_pos[active].max())} with capacity "
                f"{self.capacity} — admit shorter requests or build "
                "the session with a larger capacity")
        if self._slot_step is None:
            self._slot_step = self._make_slot_step()
        h, self._states = self._slot_step(
            self.net.params, self.net.state, self._states,
            jnp.asarray(self.slot_pos), x)
        self.slot_pos = self.slot_pos + active.astype(self.slot_pos.dtype)
        return h

    def reset_slot(self, slot: int):
        """Recycle one slot for a new request: rewind its position and
        zero its recurrent carries row-wise. Attention caches need no
        zeroing (positions beyond the slot's pos are masked and get
        overwritten as the new stream advances)."""
        self.slot_pos[slot] = 0
        for i, layer in enumerate(self.net.layers):
            if hasattr(layer, "apply_stream_bounded"):
                continue
            if hasattr(layer, "zero_state"):
                zero = layer.zero_state(1)
                self._states[i] = jax.tree_util.tree_map(
                    lambda s, z: s.at[slot].set(z[0]),
                    self._states[i], zero)

    def reset(self):
        super().reset()
        self.slot_pos = np.zeros((self.slots,), np.int32)

    def reinit_states(self):
        """Rebuild EVERY carry from scratch. The jitted slot step
        donates the state buffers, so after a step that failed
        mid-call the old carries may be deleted device arrays —
        recycling the session means fresh ones, not a reset."""
        self.slot_pos = np.zeros((self.slots,), np.int32)
        self._states = self._fresh_states()


class GraphStreamingSession(_BoundedSession):
    """The ComputationGraph counterpart of :class:`StreamingSession`
    (reference rnnTimeStep, ComputationGraph.java:2358): one compiled
    token-step over the vertex topology, fixed-capacity KV caches for
    attention vertices, recurrent carries for RNN vertices. Built via
    ``graph.streaming_session(capacity=..., batch=...)``; ``step``
    takes one array per network input and returns the network
    output(s) for the new steps. ``generate`` works for single-input
    graphs."""

    def __init__(self, graph, capacity: int, batch: int,
                 dtype=jnp.float32):
        super().__init__(capacity, batch)
        self.graph = graph
        self._states = {}
        for name, (obj, _ins) in graph.conf.vertices.items():
            if hasattr(obj, "apply_stream_bounded"):
                self._states[name] = obj.zero_stream_cache(
                    batch, self.capacity, dtype)
            elif hasattr(obj, "zero_state") and hasattr(obj,
                                                        "apply_rnn"):
                self._states[name] = obj.zero_state(batch)

    def _raw_step(self, t: int):
        graph = self.graph
        conf = graph.conf
        order = list(conf.topological_order())
        vertices = dict(conf.vertices)
        # dispatch mirrors the eager rnn_time_step
        # (computation_graph.py): Layer — not BaseLayer — is the
        # layer-vertex base class (DropoutLayer, GlobalPooling,
        # LayerNormalization, ... subclass Layer directly)
        from deeplearning4j_tpu.nn.conf.layers.base import Layer
        from deeplearning4j_tpu.nn.conf.layers.recurrent import (
            BaseRecurrentLayer)

        def step(params, layer_states, stream_states, pos, xs):
            acts = dict(zip(conf.network_inputs, xs))
            new_streams = dict(stream_states)
            for name in order:
                obj, ins = vertices[name]
                xin = [acts[i] for i in ins]
                if hasattr(obj, "apply_stream_bounded"):
                    acts[name], new_streams[name] = \
                        obj.apply_stream_bounded(
                            params[name], stream_states[name],
                            xin[0], pos)
                elif isinstance(obj, BaseRecurrentLayer):
                    acts[name], new_streams[name] = obj.apply_rnn(
                        params[name], xin[0], stream_states[name],
                        training=False)
                elif hasattr(obj, "apply_stream"):
                    # running-statistic carries (GlobalPooling):
                    # per-chunk apply() would pool only the newest
                    # chunk (the eager rnn_time_step dispatches the
                    # same way)
                    acts[name], new_streams[name] = obj.apply_stream(
                        params[name], stream_states.get(name), xin[0])
                elif isinstance(obj, Layer):
                    acts[name], _ = obj.apply(
                        params[name], layer_states[name], xin[0],
                        training=False)
                else:
                    acts[name] = obj.apply(xin)
            return tuple(acts[o] for o in conf.network_outputs), \
                new_streams

        return step

    def _make_step(self, t: int):
        return jax.jit(self._raw_step(t), donate_argnums=(2,))

    def _n_outputs(self) -> int:
        return len(self.graph.conf.network_outputs)

    def _fused_ctx(self):
        raw = self._raw_step(1)

        def feed(params, lstates, states, pos, x):
            outs, states = raw(params, lstates, states, pos, (x,))
            return outs[0], states

        return feed

    def _model_params(self):
        return self.graph.params, self.graph.state

    def step(self, *inputs):
        xs = [jnp.asarray(x) for x in inputs]
        squeeze = xs[0].ndim == 2
        if squeeze:
            xs = [x[:, None, :] for x in xs]
        B, t = xs[0].shape[0], xs[0].shape[1]
        for i, x in enumerate(xs[1:], start=1):
            if x.shape[0] != B or x.shape[1] != t:
                raise ValueError(
                    f"input {i} has (batch, t)="
                    f"{tuple(x.shape[:2])}; every input must match "
                    f"input 0's ({B}, {t}) — pos advances once per "
                    "step")
        self._check(B, t)
        outs, self._states = self._fn_for(t)(
            self.graph.params, self.graph.state, self._states,
            jnp.int32(self.pos), tuple(xs))
        self.pos += t
        if squeeze:
            outs = tuple(o[:, -1, :] if o.ndim == 3 else o
                         for o in outs)
        return outs if len(outs) > 1 else outs[0]

    def reset(self):
        self.pos = 0
        kept = {}
        for name, (obj, _ins) in self.graph.conf.vertices.items():
            if hasattr(obj, "apply_stream_bounded"):
                if name in self._states:    # pos-masked; keep as-is
                    kept[name] = self._states[name]
            elif hasattr(obj, "zero_state") and hasattr(obj,
                                                        "apply_rnn"):
                kept[name] = obj.zero_state(self.batch)
            # apply_stream running carries (GlobalPooling) drop:
            # they restart from None
        self._states = kept
