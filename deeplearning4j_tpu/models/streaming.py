"""Jitted bounded-cache streaming inference (rnnTimeStep, compiled).

``MultiLayerNetwork.rnn_time_step`` (reference
MultiLayerNetwork.java:2656) is deliberately eager: it matches the
reference contract, grows attention KV caches by concat, and pays a
Python dispatch per token-step — fine for debugging, wrong as a TPU
inference path (round-4 verdict weak #7: O(T^2) total copy traffic).

``StreamingSession`` is the TPU-first variant: every stream carry has
a STATIC shape — attention layers get a fixed-capacity KV cache
written in place with ``lax.dynamic_update_slice`` (O(t) traffic per
step), recurrent layers carry their usual state — so one XLA
executable per chunk length covers the whole decode, with a single
device dispatch per step and no retrace as the sequence grows.

Chunk lengths are compile-time buckets: the session caches one
executable per distinct chunk length it sees (a decode loop uses
exactly one, t=1; a prompt prefill adds one more). Keep chunk sizes
consistent — every new length is a new compile.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["StreamingSession"]


class StreamingSession:
    """Stateful token-streaming over a ``MultiLayerNetwork``.

    Built via ``net.streaming_session(capacity=...)``. ``step(x)``
    accepts (B, C) single steps or (B, t, C) chunks and returns the
    network output for the new steps only; feeding chunks
    sequentially equals one full-sequence forward (tested vs both the
    eager ``rnn_time_step`` and ``output``).
    """

    def __init__(self, net, capacity: int, batch: int,
                 dtype=jnp.float32):
        self.net = net
        self.capacity = int(capacity)
        self.batch = int(batch)
        self.pos = 0                      # host mirror of the carry
        self._step_cache = {}             # chunk length -> jitted fn
        self._states = []
        for layer in net.layers:
            if hasattr(layer, "apply_stream_bounded"):
                self._states.append(layer.zero_stream_cache(
                    batch, self.capacity, dtype))
            elif hasattr(layer, "zero_state"):
                self._states.append(layer.zero_state(batch))
            else:
                self._states.append(None)

    # ------------------------------------------------------------------

    def _make_step(self, t: int):
        net = self.net
        layers = list(net.layers)
        preprocessors = dict(net.conf.preprocessors)

        def step(params, layer_states, stream_states, pos, x):
            h = x
            new_streams = list(stream_states)
            for i, layer in enumerate(layers):
                if i in preprocessors:
                    h = preprocessors[i](h)
                if hasattr(layer, "apply_stream_bounded"):
                    h, new_streams[i] = layer.apply_stream_bounded(
                        params[i], stream_states[i], h, pos)
                elif hasattr(layer, "zero_state") and hasattr(
                        layer, "apply_rnn"):
                    h, new_streams[i] = layer.apply_rnn(
                        params[i], h, stream_states[i],
                        training=False)
                else:
                    h, _ = layer.apply(params[i], layer_states[i], h,
                                       training=False)
            return h, new_streams

        return jax.jit(step)

    def step(self, x):
        """Feed the next chunk; returns outputs for the new steps.
        (B, C) input -> (B, C) output (single step, squeezed);
        (B, t, C) -> (B, t, C)."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        B, t, _ = x.shape
        if B != self.batch:
            raise ValueError(f"batch {B} != session batch "
                             f"{self.batch}")
        if self.pos + t > self.capacity:
            raise ValueError(
                f"stream overflow: pos {self.pos} + chunk {t} exceeds "
                f"capacity {self.capacity} — create the session with "
                f"a larger capacity or reset()")
        fn = self._step_cache.get(t)
        if fn is None:
            fn = self._step_cache[t] = self._make_step(t)
        h, self._states = fn(self.net.params, self.net.state,
                             self._states, jnp.int32(self.pos), x)
        self.pos += t
        if squeeze and h.ndim == 3:
            h = h[:, -1, :]
        return h

    def reset(self):
        """Start a new sequence: rewind the position. Attention
        caches need no zeroing (slots beyond ``pos`` are masked and
        overwritten), recurrent carries do."""
        self.pos = 0
        for i, layer in enumerate(self.net.layers):
            if hasattr(layer, "zero_state") and not hasattr(
                    layer, "apply_stream_bounded"):
                self._states[i] = layer.zero_state(self.batch)
