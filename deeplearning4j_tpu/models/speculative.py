"""Draft-model speculative decoding with accept-prefix semantics.

Autoregressive greedy decode pays one target-model dispatch per
token. Speculative decoding (the Leviathan/Chen draft-verify scheme,
greedy variant) lets a SMALL draft model propose ``k`` tokens and the
target model verify all of them in ONE chunked step:

- the draft proposes greedily from its own KV state — here as one
  fused ``lax.scan`` program, so a whole proposal round is two
  dispatches (feed the last accepted token, scan k proposals);
- the target consumes ``[last_accepted] + proposals[:-1]`` as a
  single (1, k) chunk — one dispatch — giving its next-token argmax
  at every position;
- the longest prefix of proposals that matches the target's argmax
  chain is ACCEPTED; on a mismatch the target's own argmax at the
  mismatch position is emitted instead (the "bonus" correction).

Because every emitted token is, by construction, exactly the target's
greedy argmax given the emitted history, the output is IDENTICAL to
vanilla greedy decode of the target alone (tested) — the draft only
changes how many dispatches that sequence costs: ``2 + 1`` per round
of up to ``k`` tokens instead of ``k``. Rejected proposals leave
stale KV entries behind; rewinding ``session.pos`` is all the
rollback needed — the bounded sessions mask every cache position
``>= pos``, and later writes overwrite the stale slots
(models/streaming.py). That masking trick is also why only models
whose streaming state is pure KV cache qualify: a recurrent carry
cannot rewind, so such layers are rejected at construction.

Acceptance telemetry rides the shared metrics registry
(``spec_tokens_proposed_total`` / ``spec_tokens_accepted_total``;
the acceptance rate is their ratio) so serving dashboards can see
when a draft has drifted too far from its target to pay for itself.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["SpeculativeDecoder"]


def _reject_unrewindable(net, role: str) -> None:
    for i, layer in enumerate(net.layers):
        if hasattr(layer, "apply_stream_bounded"):
            continue
        if hasattr(layer, "zero_state") or hasattr(layer,
                                                   "apply_stream"):
            raise ValueError(
                f"{role} model layer {i} ({type(layer).__name__}) "
                "carries non-KV streaming state (recurrent carry or "
                "running statistic); speculative decode rolls back "
                "by rewinding pos, which only KV caches support")


class SpeculativeDecoder:
    """Greedy speculative decoding over two bounded streaming
    sessions (target + draft). ``generate(prompt, n_tokens)`` returns
    ids bit-identical to the target's own greedy decode.

    ``capacity`` needs ``prompt + n_tokens + k`` headroom: a verify
    chunk may overshoot the final length by up to ``k`` rejected
    positions before the rewind."""

    def __init__(self, target_net, draft_net, k: int = 4,
                 capacity: int = 256, registry=None,
                 endpoint: str = "speculative"):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        _reject_unrewindable(target_net, "target")
        _reject_unrewindable(draft_net, "draft")
        self.k = int(k)
        self.capacity = int(capacity)
        self.target = target_net.streaming_session(capacity=capacity,
                                                   batch=1)
        self.draft = draft_net.streaming_session(capacity=capacity,
                                                 batch=1)
        # lifetime acceptance accounting (plain ints for tests /
        # in-process callers, registry counters for dashboards —
        # instruments created once HERE, never per round)
        self.tokens_proposed = 0
        self.tokens_accepted = 0
        self._proposed_ctr = self._accepted_ctr = None
        if registry is not None:
            lbl = {"endpoint": endpoint}
            self._proposed_ctr = registry.counter(
                "spec_tokens_proposed_total",
                help="draft tokens proposed for verification",
                labels=lbl)
            self._accepted_ctr = registry.counter(
                "spec_tokens_accepted_total",
                help="draft tokens accepted by the target "
                     "(acceptance rate = accepted / proposed)",
                labels=lbl)

    @property
    def acceptance_rate(self) -> float:
        if not self.tokens_proposed:
            return 0.0
        return self.tokens_accepted / self.tokens_proposed

    def _count(self, proposed: int, accepted: int) -> None:
        self.tokens_proposed += proposed
        self.tokens_accepted += accepted
        if self._proposed_ctr is not None:
            self._proposed_ctr.inc(proposed)
            self._accepted_ctr.inc(accepted)

    def generate(self, prompt, n_tokens: int) -> np.ndarray:
        """Greedy-decode ``n_tokens`` ids after ``prompt`` (a 1-d or
        (1, T0) id sequence). Returns a (n_tokens,) int array equal
        to the target's vanilla greedy decode."""
        import jax.numpy as jnp
        prompt = np.asarray(prompt).reshape(1, -1)
        T0 = prompt.shape[1]
        n_tokens = int(n_tokens)
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1, got {n_tokens}")
        if T0 + n_tokens + self.k > self.capacity:
            raise ValueError(
                f"prompt ({T0}) + n_tokens ({n_tokens}) + k "
                f"({self.k}) verify headroom exceeds capacity "
                f"{self.capacity}")
        tgt, drf, k = self.target, self.draft, self.k
        tgt.reset()
        drf.reset()
        feed = lambda toks: np.asarray(toks, np.float32
                                       ).reshape(1, -1, 1)
        # prefill both models; the FIRST token comes straight from
        # the target (no draft involvement, same as vanilla greedy)
        p_t = np.asarray(tgt.step(feed(prompt[0])))
        drf.step(feed(prompt[0]))
        last_tok = int(np.argmax(p_t[0, -1]))
        emitted = [last_tok]
        rng = jnp.zeros((2,), jnp.uint32)     # greedy: RNG unused
        while len(emitted) < n_tokens:
            # draft round: consume the last accepted token (one
            # dispatch), then propose k more as ONE fused scan
            d_pos0 = drf.pos
            d_probs = np.asarray(drf.step(feed([last_tok])))
            props = [int(t) for t in np.asarray(
                drf._generate_fused(jnp.asarray(d_probs[:, 0]), k,
                                    0.0, rng))[0]]
            # target verifies the whole round in one chunked step:
            # probs[j] is the target's next-token distribution after
            # consuming [last_tok] + props[:j]
            t_pos0 = tgt.pos
            chunk = [last_tok] + props[:-1]
            P = np.asarray(tgt.step(feed(chunk)))[0]      # (k, V)
            argmax = np.argmax(P, axis=-1)
            n_acc = 0
            while n_acc < k and props[n_acc] == int(argmax[n_acc]):
                n_acc += 1
            self._count(proposed=k, accepted=n_acc)
            if n_acc == k:
                # every proposal matched the target's argmax chain:
                # all of the chunk's KV entries are valid, and the
                # last proposal becomes the next round's feed
                emitted.extend(props)
                last_tok = props[-1]
            else:
                # accept the matching prefix, emit the target's own
                # argmax at the first mismatch, rewind both sessions
                # past the garbage KV (masked until overwritten)
                emitted.extend(props[:n_acc])
                last_tok = int(argmax[n_acc])
                emitted.append(last_tok)
                tgt.pos = t_pos0 + 1 + n_acc
            drf.pos = d_pos0 + 1 + min(n_acc, k - 1)
        return np.asarray(emitted[:n_tokens], np.int64)
