from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph

__all__ = ["MultiLayerNetwork", "ComputationGraph"]
