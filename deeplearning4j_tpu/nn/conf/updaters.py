"""Updater (optimizer) configs + learning-rate schedules.

Mirrors the reference's updater vocabulary (ND4J
org.nd4j.linalg.learning.config.* referenced from
NeuralNetConfiguration.java:1081-1096: Sgd/Adam/AdaMax/Nesterovs/
AdaGrad/AdaDelta/RmsProp/NoOp) and the lr decay policies
(UpdaterBlock.applyLrDecayPolicy: exponential/inverse/poly/sigmoid/
step/schedule). Configs are plain dicts (JSON-stable); ``to_optax``
compiles one to an optax GradientTransformation — the whole updater
runs inside the jitted train step.
"""

from __future__ import annotations

from typing import Optional

import optax

__all__ = ["to_optax", "make_schedule", "sgd", "adam", "adamax", "nesterovs",
           "adagrad", "adadelta", "rmsprop", "noop", "amsgrad", "nadam"]


# ---- config constructors (builder sugar) ----

def sgd(lr=0.1, schedule=None):
    return {"type": "sgd", "lr": lr, "schedule": schedule}


def adam(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, schedule=None):
    return {"type": "adam", "lr": lr, "beta1": beta1, "beta2": beta2,
            "eps": eps, "schedule": schedule}


def amsgrad(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, schedule=None):
    return {"type": "amsgrad", "lr": lr, "beta1": beta1, "beta2": beta2,
            "eps": eps, "schedule": schedule}


def nadam(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, schedule=None):
    return {"type": "nadam", "lr": lr, "beta1": beta1, "beta2": beta2,
            "eps": eps, "schedule": schedule}


def adamax(lr=2e-3, beta1=0.9, beta2=0.999, eps=1e-8, schedule=None):
    return {"type": "adamax", "lr": lr, "beta1": beta1, "beta2": beta2,
            "eps": eps, "schedule": schedule}


def nesterovs(lr=0.1, momentum=0.9, schedule=None):
    return {"type": "nesterovs", "lr": lr, "momentum": momentum,
            "schedule": schedule}


def adagrad(lr=0.1, eps=1e-6, schedule=None):
    return {"type": "adagrad", "lr": lr, "eps": eps, "schedule": schedule}


def adadelta(rho=0.95, eps=1e-6):
    return {"type": "adadelta", "rho": rho, "eps": eps}


def rmsprop(lr=1e-3, decay=0.95, eps=1e-8, schedule=None):
    return {"type": "rmsprop", "lr": lr, "decay": decay, "eps": eps,
            "schedule": schedule}


def noop():
    return {"type": "noop"}


# ---- schedules (ISchedule / lr decay policies) ----

def make_schedule(base_lr: float, sched: Optional[dict]):
    """dict → optax schedule. Types: 'exponential' {gamma}, 'inverse'
    {gamma, power}, 'poly' {power, max_iter}, 'sigmoid' {gamma, step},
    'step' {decay_rate, step}, 'map' {values: {iter: lr}}, 'warmup_cosine'
    {warmup_steps, total_steps, [end_lr]}."""
    if sched is None:
        return base_lr
    t = sched["type"]
    if t == "exponential":
        g = sched.get("gamma", 0.99)
        return lambda i: base_lr * g ** i
    if t == "inverse":
        g, p = sched.get("gamma", 1e-2), sched.get("power", 1.0)
        return lambda i: base_lr / (1 + g * i) ** p
    if t == "poly":
        p = sched.get("power", 1.0)
        mx = sched.get("max_iter", 10000)
        import jax.numpy as jnp
        return lambda i: base_lr * (1 - jnp.minimum(i, mx) / mx) ** p
    if t == "sigmoid":
        g, s = sched.get("gamma", 0.5), sched.get("step", 10)
        import jax.numpy as jnp
        return lambda i: base_lr / (1 + jnp.exp(-g * (i - s)))
    if t == "step":
        d, s = sched.get("decay_rate", 0.1), sched.get("step", 1000)
        import jax.numpy as jnp
        return lambda i: base_lr * d ** jnp.floor(i / s)
    if t == "map":
        import jax.numpy as jnp
        pairs = sorted((int(k), float(v))
                       for k, v in sched["values"].items())
        def f(i):
            lr = base_lr
            for it, v in pairs:
                lr = jnp.where(i >= it, v, lr)
            return lr
        return f
    if t == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, base_lr, sched.get("warmup_steps", 0),
            sched.get("total_steps", 10000), sched.get("end_lr", 0.0))
    raise ValueError(f"Unknown schedule type '{t}'")


def to_optax(cfg: Optional[dict]) -> optax.GradientTransformation:
    """Compile an updater config dict to optax."""
    if cfg is None:
        cfg = sgd()
    t = cfg.get("type", "sgd")
    lr = make_schedule(cfg.get("lr", 0.1), cfg.get("schedule"))
    if t == "sgd":
        return optax.sgd(lr)
    if t == "adam":
        return optax.adam(lr, b1=cfg.get("beta1", 0.9),
                          b2=cfg.get("beta2", 0.999),
                          eps=cfg.get("eps", 1e-8))
    if t == "amsgrad":
        return optax.amsgrad(lr, b1=cfg.get("beta1", 0.9),
                             b2=cfg.get("beta2", 0.999),
                             eps=cfg.get("eps", 1e-8))
    if t == "nadam":
        return optax.nadam(lr, b1=cfg.get("beta1", 0.9),
                           b2=cfg.get("beta2", 0.999),
                           eps=cfg.get("eps", 1e-8))
    if t == "adamax":
        return optax.adamax(lr, b1=cfg.get("beta1", 0.9),
                            b2=cfg.get("beta2", 0.999),
                            eps=cfg.get("eps", 1e-8))
    if t == "nesterovs":
        return optax.sgd(lr, momentum=cfg.get("momentum", 0.9),
                         nesterov=True)
    if t == "adagrad":
        return optax.adagrad(lr, eps=cfg.get("eps", 1e-6))
    if t == "adadelta":
        return optax.adadelta(rho=cfg.get("rho", 0.95),
                              eps=cfg.get("eps", 1e-6))
    if t == "rmsprop":
        return optax.rmsprop(lr, decay=cfg.get("decay", 0.95),
                             eps=cfg.get("eps", 1e-8))
    if t == "noop":
        return optax.set_to_zero()
    raise ValueError(f"Unknown updater type '{t}'")
