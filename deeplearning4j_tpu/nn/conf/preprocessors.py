"""Input preprocessors: reshape/transpose between layer families.

Mirrors nn/conf/preprocessor/*.java (12 classes). The executors insert
these between layers whose InputTypes disagree, exactly like
``MultiLayerConfiguration.Builder`` does via
``InputType.getPreProcessorForInputType``. Conv activations are NHWC
(TPU-native) rather than the reference's NCHW; the *Flat* forms use
channel-last flattening accordingly (documented divergence — Keras
import compensates when loading NCHW-trained weights).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

__all__ = ["InputPreProcessor", "preprocessor_from_dict",
           "CnnToFeedForwardPreProcessor", "FeedForwardToCnnPreProcessor",
           "RnnToFeedForwardPreProcessor", "FeedForwardToRnnPreProcessor",
           "CnnToRnnPreProcessor", "RnnToCnnPreProcessor",
           "auto_preprocessor"]

_PP_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _PP_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d: Optional[dict]):
    if d is None:
        return None
    d = dict(d)
    t = d.pop("@type")
    return _PP_REGISTRY[t](**d)


@dataclasses.dataclass
class InputPreProcessor:
    def __call__(self, x):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        d.update(dataclasses.asdict(self))
        return d


@_register
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """(nn/conf/preprocessor/CnnToFeedForwardPreProcessor.java)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, t: InputType) -> InputType:
        return InputType.feed_forward(t.flat_size())


@_register
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """(nn/conf/preprocessor/FeedForwardToCnnPreProcessor.java).
    Reshapes (B, H*W*C) → (B,H,W,C)."""
    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x):
        if x.ndim == 4:
            return x
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, t: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


@_register
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """(B,T,C) → (B*T,C) (nn/conf/preprocessor/RnnToFeedForward...).
    NOTE: executors apply dense layers time-distributed on 3-d input
    directly, so this is mainly for explicit-config parity."""

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, t: InputType) -> InputType:
        return InputType.feed_forward(t.size)


@_register
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    timesteps: int = 0

    def __call__(self, x):
        return x.reshape(-1, self.timesteps, x.shape[-1])

    def output_type(self, t: InputType) -> InputType:
        return InputType.recurrent(t.size, self.timesteps or None)


@_register
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """(B,H,W,C) → (B,T=H,  W*C) — treat rows as timesteps (matches the
    reference's flattening of spatial dims to a sequence)."""
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x):
        b, h, w, c = x.shape
        return x.reshape(b, h, w * c)

    def output_type(self, t: InputType) -> InputType:
        return InputType.recurrent(t.width * t.channels, t.height)


@_register
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x):
        b = x.shape[0]
        return x.reshape(b, self.height, self.width, self.channels)

    def output_type(self, t: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)


def auto_preprocessor(have: InputType, layer) -> Optional[InputPreProcessor]:
    """Pick the preprocessor between activation type ``have`` and the
    next layer, mirroring InputType.getPreProcessorForInputType +
    InputTypeUtil auto-insertion in MultiLayerConfiguration.Builder."""
    from deeplearning4j_tpu.nn.conf.layers.convolutional import (
        ConvolutionLayer, Convolution1DLayer, ZeroPaddingLayer,
        UpsamplingLayer, CroppingLayer, SpaceToDepthLayer,
        SpaceToBatchLayer)
    from deeplearning4j_tpu.nn.conf.layers.pooling import (
        SubsamplingLayer, Subsampling1DLayer, GlobalPoolingLayer)
    from deeplearning4j_tpu.nn.conf.layers.recurrent import (
        BaseRecurrentLayer, Bidirectional, LastTimeStep)
    from deeplearning4j_tpu.nn.conf.layers.normalization import (
        BatchNormalization, LocalResponseNormalization)
    from deeplearning4j_tpu.nn.conf.layers.output import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.layers.special import Yolo2OutputLayer

    wants_cnn = isinstance(layer, (ConvolutionLayer, SubsamplingLayer,
                                   LocalResponseNormalization,
                                   ZeroPaddingLayer, UpsamplingLayer,
                                   CroppingLayer, SpaceToDepthLayer,
                                   SpaceToBatchLayer,
                                   Yolo2OutputLayer)) and not \
        isinstance(layer, (Convolution1DLayer, Subsampling1DLayer))
    wants_rnn = isinstance(layer, (BaseRecurrentLayer, Bidirectional,
                                   LastTimeStep, RnnOutputLayer,
                                   Convolution1DLayer, Subsampling1DLayer))

    if have.kind == "cnnflat" and wants_cnn:
        return FeedForwardToCnnPreProcessor(have.height, have.width,
                                            have.channels)
    if have.kind == "cnn" and not wants_cnn and not wants_rnn and not \
            isinstance(layer, (BatchNormalization, GlobalPoolingLayer)):
        # dense/output after conv: flatten
        return CnnToFeedForwardPreProcessor(have.height, have.width,
                                            have.channels)
    if have.kind == "cnn" and wants_rnn:
        return CnnToRnnPreProcessor(have.height, have.width, have.channels)
    if have.kind == "cnnflat" and not wants_cnn:
        return None
    return None
