"""ComputationGraphConfiguration + GraphBuilder.

Mirrors nn/conf/ComputationGraphConfiguration.java (836 LoC) and its
GraphBuilder: named inputs, vertices (layers or GraphVertex ops) wired
by name, named outputs; topological sort computed once and cached
(reference: ComputationGraph.topologicalSortOrder, :1187).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import GraphVertex, vertex_from_dict
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.conf.multi_layer import migrate_config, \
    FORMAT_VERSION

__all__ = ["ComputationGraphConfiguration", "GraphBuilder"]


class ComputationGraphConfiguration:
    def __init__(self, conf: NeuralNetConfiguration,
                 inputs: List[str],
                 vertices: Dict[str, Tuple[object, List[str]]],
                 outputs: List[str],
                 input_types: Optional[List[InputType]] = None):
        self.conf = conf
        self.network_inputs = list(inputs)
        self.vertices = dict(vertices)      # name -> (Layer|GraphVertex, ins)
        self.network_outputs = list(outputs)
        self.input_types = input_types
        self._topo: Optional[List[str]] = None
        self._vertex_input_types: Dict[str, InputType] = {}
        if input_types is not None:
            self._infer_shapes()

    # ---- topology ----
    def topological_order(self) -> List[str]:
        """Kahn's algorithm over vertex names; cached (reference
        ComputationGraph.java:1187)."""
        if self._topo is not None:
            return self._topo
        indeg = {}
        consumers: Dict[str, List[str]] = {}
        for name, (_, ins) in self.vertices.items():
            indeg[name] = 0
            for i in ins:
                if i not in self.network_inputs:
                    indeg[name] += 1
        for name, (_, ins) in self.vertices.items():
            for i in ins:
                if i in self.vertices:
                    consumers.setdefault(i, []).append(name)
        queue = sorted(n for n, d in indeg.items() if d == 0)
        order = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for c in consumers.get(n, []):
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle involving {sorted(cyc)}")
        self._topo = order
        return order

    def _infer_shapes(self):
        types: Dict[str, InputType] = dict(zip(self.network_inputs,
                                               self.input_types))
        for name in self.topological_order():
            obj, ins = self.vertices[name]
            in_types = [types[i] for i in ins]
            if isinstance(obj, Layer):
                obj.set_n_in(in_types[0])
                self._vertex_input_types[name] = in_types[0]
                types[name] = obj.output_type(in_types[0])
            else:
                types[name] = obj.output_type(*in_types)
        self.activation_types = types

    def vertex_input_type(self, name: str) -> Optional[InputType]:
        return self._vertex_input_types.get(name)

    # ---- serde ----
    def to_dict(self) -> dict:
        vd = {}
        for name, (obj, ins) in self.vertices.items():
            vd[name] = {
                "kind": "layer" if isinstance(obj, Layer) else "vertex",
                "config": obj.to_dict(),
                "inputs": list(ins),
            }
        return {
            "format_version": FORMAT_VERSION,
            "network_type": "ComputationGraph",
            "global": self.conf.global_to_dict(),
            "inputs": self.network_inputs,
            "input_types": ([t.to_dict() for t in self.input_types]
                            if self.input_types else None),
            "vertices": vd,
            "outputs": self.network_outputs,
        }

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        d = migrate_config(d)
        conf = NeuralNetConfiguration.global_from_dict(d.get("global", {}))
        vertices = {}
        for name, vd in d["vertices"].items():
            obj = (layer_from_dict(vd["config"]) if vd["kind"] == "layer"
                   else vertex_from_dict(vd["config"]))
            vertices[name] = (obj, list(vd["inputs"]))
        its = d.get("input_types")
        return ComputationGraphConfiguration(
            conf, d["inputs"], vertices, d["outputs"],
            [InputType.from_dict(t) for t in its] if its else None)

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), indent=kw.pop("indent", 2), **kw)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))

    def clone(self) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(self.to_dict())


class GraphBuilder:
    """ComputationGraphConfiguration.GraphBuilder equivalent."""

    def __init__(self, conf: NeuralNetConfiguration):
        self._conf = conf
        self._inputs: List[str] = []
        self._vertices: Dict[str, Tuple[object, List[str]]] = {}
        self._outputs: List[str] = []
        self._input_types: Optional[List[InputType]] = None

    def add_inputs(self, *names: str):
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType):
        self._input_types = list(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str):
        layer = self._conf.stamp_defaults(layer)
        layer.name = name
        self._vertices[name] = (layer, list(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str):
        self._vertices[name] = (vertex, list(inputs))
        return self

    def set_outputs(self, *names: str):
        self._outputs = list(names)
        return self

    def build(self) -> ComputationGraphConfiguration:
        for name, (_, ins) in self._vertices.items():
            for i in ins:
                if i not in self._vertices and i not in self._inputs:
                    raise ValueError(f"Vertex '{name}' references unknown "
                                     f"input '{i}'")
        for o in self._outputs:
            if o not in self._vertices:
                raise ValueError(f"Output '{o}' is not a vertex")
        return ComputationGraphConfiguration(
            self._conf, self._inputs, self._vertices, self._outputs,
            self._input_types)
