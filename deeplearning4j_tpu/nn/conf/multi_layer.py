"""MultiLayerConfiguration: ordered layer stack + preprocessors + serde.

Mirrors nn/conf/MultiLayerConfiguration.java (578 LoC): holds the layer
configs, auto-inserted preprocessors, input type, and round-trips to
JSON/YAML. The JSON schema carries a ``format_version`` for forward
migration (the analog of the reference's legacy-config deserializers,
nn/conf/serde/BaseNetConfigDeserializer.java — regression-tested
formats are a first-class contract here too).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor, auto_preprocessor, preprocessor_from_dict,
)

__all__ = ["MultiLayerConfiguration", "FORMAT_VERSION"]

FORMAT_VERSION = 1


class MultiLayerConfiguration:
    def __init__(self, conf: NeuralNetConfiguration, layers: List[Layer],
                 input_type: Optional[InputType] = None,
                 preprocessors: Optional[Dict[int, InputPreProcessor]] = None):
        self.conf = conf
        self.layers = layers
        self.input_type = input_type
        # index -> preprocessor applied to that layer's INPUT
        self.preprocessors: Dict[int, InputPreProcessor] = \
            dict(preprocessors or {})
        if input_type is not None and not self.preprocessors:
            self._infer_shapes()

    def _infer_shapes(self):
        """Walk the stack inferring nIn and inserting preprocessors —
        the ListBuilder.build() shape pass (InputTypeUtil semantics)."""
        t = self.input_type
        for i, layer in enumerate(self.layers):
            pp = auto_preprocessor(t, layer)
            if pp is not None:
                self.preprocessors[i] = pp
                t = pp.output_type(t)
            layer.set_n_in(t)
            t = layer.output_type(t)

    def output_type(self) -> InputType:
        t = self.input_type
        for i, layer in enumerate(self.layers):
            if i in self.preprocessors:
                t = self.preprocessors[i].output_type(t)
            t = layer.output_type(t)
        return t

    @property
    def n_layers(self) -> int:
        return len(self.layers)

    # ---- serde ----
    def to_dict(self) -> dict:
        return {
            "format_version": FORMAT_VERSION,
            "network_type": "MultiLayerNetwork",
            "global": self.conf.global_to_dict(),
            "input_type": (self.input_type.to_dict()
                           if self.input_type else None),
            "layers": [l.to_dict() for l in self.layers],
            "preprocessors": {str(i): p.to_dict()
                              for i, p in self.preprocessors.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        d = migrate_config(d)
        conf = NeuralNetConfiguration.global_from_dict(d.get("global", {}))
        layers = [layer_from_dict(ld) for ld in d["layers"]]
        it = d.get("input_type")
        pps = {int(i): preprocessor_from_dict(p)
               for i, p in (d.get("preprocessors") or {}).items()}
        mlc = MultiLayerConfiguration(conf, layers,
                                      InputType.from_dict(it) if it else None,
                                      preprocessors=pps)
        return mlc

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), indent=kw.pop("indent", 2), **kw)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    def to_yaml(self) -> str:
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))

    def clone(self) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(self.to_dict())


def migrate_config(d: dict) -> dict:
    """Version-migration hook (analog of BaseNetConfigDeserializer's
    legacy-format handling). Each released format_version gets an
    upgrade step here; regression tests pin old JSON files."""
    v = d.get("format_version", FORMAT_VERSION)
    if v > FORMAT_VERSION:
        raise ValueError(f"Config format_version {v} is newer than this "
                         f"build supports ({FORMAT_VERSION})")
    # v1 → current: nothing yet
    return d
