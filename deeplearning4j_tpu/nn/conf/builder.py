"""NeuralNetConfiguration builder — the config DSL entry point.

Mirrors nn/conf/NeuralNetConfiguration.java's fluent Builder +
ListBuilder (:225-278): global defaults (seed, updater, weight init,
activation, regularization, dropout) that are stamped onto each layer
unless the layer overrides them, then ``.list()...build()`` →
:class:`MultiLayerConfiguration` or ``.graph_builder()`` →
:class:`ComputationGraphConfiguration`.

Python-idiomatic usage keeps the reference's shape::

    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(updaters.adam(1e-3))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.nn.conf import updaters as updaters_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import BaseLayer, Layer

__all__ = ["NeuralNetConfiguration"]


_DEFAULTABLE_FIELDS = ("activation", "weight_init", "l1", "l2", "l1_bias",
                       "l2_bias", "updater", "gradient_normalization",
                       "gradient_normalization_threshold")


class NeuralNetConfiguration:
    """Global training/config defaults (one per network)."""

    def __init__(self):
        self.seed: int = 0
        self.updater_cfg: Optional[dict] = None
        self.defaults: Dict[str, Any] = {}
        self.dropout: float = 0.0
        self.mini_batch: bool = True
        self.max_num_line_search_iterations: int = 5
        self.optimization_algo: str = "stochastic_gradient_descent"
        self.gradient_clip: Optional[dict] = None   # {"type": "norm"|"value"|
                                                    #  "norm_per_param", "v":x}
        self.tbptt: Optional[dict] = None   # {"fwd_length": n, "bwd_length": n}

    # ---- fluent builder (mirrors Builder method names, snake_cased) ----
    @staticmethod
    def builder() -> "NeuralNetConfiguration":
        return NeuralNetConfiguration()

    def seed_(self, s: int):
        self.seed = int(s)
        return self

    # keep java-style name too
    def set_seed(self, s: int):
        return self.seed_(s)

    def updater(self, cfg: dict):
        self.updater_cfg = cfg
        return self

    def learning_rate(self, lr: float):
        if self.updater_cfg is None:
            self.updater_cfg = updaters_mod.sgd(lr)
        else:
            self.updater_cfg = {**self.updater_cfg, "lr": lr}
        return self

    def weight_init(self, scheme: str, distribution: Optional[dict] = None):
        self.defaults["weight_init"] = scheme
        if distribution is not None:
            self.defaults["weight_distribution"] = distribution
        return self

    def activation(self, a: str):
        self.defaults["activation"] = a
        return self

    def l1(self, v: float):
        self.defaults["l1"] = v
        return self

    def l2(self, v: float):
        self.defaults["l2"] = v
        return self

    def drop_out(self, drop_prob: float):
        self.dropout = drop_prob
        return self

    def gradient_normalization(self, kind: str, threshold: float = 1.0):
        """kind ∈ {'clip_l2_per_layer','clip_element_wise',
        'renormalize_l2_per_layer','clip_l2_per_param_type'} — mirrors
        GradientNormalization enum."""
        self.defaults["gradient_normalization"] = kind
        self.defaults["gradient_normalization_threshold"] = threshold
        return self

    def clip_gradient_norm(self, v: float):
        self.gradient_clip = {"type": "norm", "v": v}
        return self

    def clip_gradient_value(self, v: float):
        self.gradient_clip = {"type": "value", "v": v}
        return self

    def optimization_algorithm(self, algo: str):
        self.optimization_algo = algo
        return self

    def backprop_type(self, kind: str, fwd_length: int = 20,
                      bwd_length: int = 20):
        if kind.lower() in ("truncatedbptt", "tbptt", "truncated_bptt"):
            self.tbptt = {"fwd_length": fwd_length, "bwd_length": bwd_length}
        return self

    # ---- terminals ----
    def list(self) -> "ListBuilder":
        return ListBuilder(self)

    def graph_builder(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
        return GraphBuilder(self)

    def stamp_defaults(self, layer: Layer) -> Layer:
        """Apply global defaults to fields the layer left at their
        dataclass defaults (reference: Builder.layer(...) copies global
        conf into each NeuralNetConfiguration clone)."""
        if isinstance(layer, BaseLayer):
            field_defaults = {f.name: f.default
                              for f in dataclasses.fields(type(layer))}
            base_defaults = {f.name: f.default
                             for f in dataclasses.fields(BaseLayer)}
            for k, v in self.defaults.items():
                # stamp only fields the user left at the default AND whose
                # subclass didn't deliberately customize the default (e.g.
                # OutputLayer.activation = softmax stays softmax)
                if (k in field_defaults
                        and getattr(layer, k) == field_defaults[k]
                        and field_defaults[k] == base_defaults.get(
                            k, field_defaults[k])):
                    setattr(layer, k, v)
            if layer.updater is None and self.updater_cfg is not None:
                # leave None → falls back to global updater at train time
                pass
        if self.dropout and layer.dropout == 0.0:
            layer.dropout = self.dropout
        return layer

    def global_to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "updater": self.updater_cfg,
            "defaults": self.defaults,
            "dropout": self.dropout,
            "optimization_algo": self.optimization_algo,
            "gradient_clip": self.gradient_clip,
            "tbptt": self.tbptt,
        }

    @staticmethod
    def global_from_dict(d: dict) -> "NeuralNetConfiguration":
        c = NeuralNetConfiguration()
        c.seed = d.get("seed", 0)
        c.updater_cfg = d.get("updater")
        c.defaults = d.get("defaults", {}) or {}
        c.dropout = d.get("dropout", 0.0)
        c.optimization_algo = d.get("optimization_algo",
                                    "stochastic_gradient_descent")
        c.gradient_clip = d.get("gradient_clip")
        c.tbptt = d.get("tbptt")
        return c


class ListBuilder:
    """NeuralNetConfiguration.ListBuilder (:225): ordered layer stack →
    MultiLayerConfiguration."""

    def __init__(self, conf: NeuralNetConfiguration):
        self._conf = conf
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None

    def layer(self, layer: Layer, index: Optional[int] = None):
        layer = self._conf.stamp_defaults(layer)
        if index is None:
            self._layers.append(layer)
        else:
            while len(self._layers) <= index:
                self._layers.append(None)
            self._layers[index] = layer
        return self

    def set_input_type(self, t: InputType):
        self._input_type = t
        return self

    def build(self):
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration)
        if any(l is None for l in self._layers):
            raise ValueError("Gap in layer indices")
        return MultiLayerConfiguration(self._conf, list(self._layers),
                                       self._input_type)
