from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration

__all__ = [
    "InputType",
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "ComputationGraphConfiguration",
]
