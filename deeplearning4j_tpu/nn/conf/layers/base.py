"""Base layer config classes + serde registry.

Mirrors nn/conf/layers/Layer.java / BaseLayer.java /
FeedForwardLayer.java: common hyperparameters (activation, weight init,
regularization, dropout, updater override, constraints) live on the
base class; subclasses add geometry. JSON round-trip uses a
``@register_layer`` type registry, the analog of Jackson's
``@JsonSubTypes`` on the reference's Layer class hierarchy.

Functional protocol (replaces nn/api/Layer.activate/backpropGradient):

- ``output_type(input_type)``: config-time shape inference
  (reference: Layer.getOutputType, InputTypeUtil)
- ``initialize(key, input_type)``: returns ``(params, state)`` — both
  dicts of arrays; ``params`` is trained, ``state`` carries
  non-trained buffers (e.g. batchnorm running stats)
- ``apply(params, state, x, *, training, rng, mask)``: pure forward,
  returns ``(out, new_state)``. jit/vmap/grad-safe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.weights import init_weight

__all__ = ["Layer", "BaseLayer", "FeedForwardLayer", "register_layer",
           "layer_from_dict", "LAYER_REGISTRY"]

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    """Class decorator: register for JSON round-trip by type name."""
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_dict(d: dict) -> "Layer":
    d = dict(d)
    tname = d.pop("@type")
    if tname not in LAYER_REGISTRY:
        raise ValueError(f"Unknown layer type '{tname}' "
                         f"(known: {sorted(LAYER_REGISTRY)})")
    return LAYER_REGISTRY[tname].from_dict(d)


@dataclasses.dataclass
class Layer:
    """Root of the layer-config hierarchy (nn/conf/layers/Layer.java)."""

    name: Optional[str] = None
    # Probability of DROPPING an input activation (inverted-dropout scaling).
    # NOTE: the reference's dropOut(x) is the probability of *retaining*
    # (nn/conf/layers/Layer.java dropOut javadoc); Keras import converts.
    dropout: float = 0.0
    constraints: Tuple[dict, ...] = ()

    # True iff apply() on a (B, T, ...) input is exact when T is only a
    # LOCAL chunk of the sequence — i.e. the layer is pointwise in time
    # (or, like attention, routes itself through the ring). Gates the
    # wrapper's sequence-parallel train step. Plain class attribute
    # (no annotation) so dataclasses don't treat it as a field.
    seq_parallelizable = False

    # ---- shape inference ----
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def set_n_in(self, input_type: InputType) -> None:
        """Infer nIn-style geometry from the incoming type (override)."""

    # ---- params ----
    def initialize(self, key, input_type: InputType):
        return {}, {}

    def num_params(self, input_type: InputType) -> int:
        params, _ = self.initialize(jax.random.PRNGKey(0), input_type)
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))

    # ---- forward ----
    def apply(self, params, state, x, *, training: bool = False, rng=None,
              mask=None):
        raise NotImplementedError

    def has_loss(self) -> bool:
        return False

    def regularization_loss(self, params) -> jnp.ndarray:
        return jnp.zeros(())

    # ---- dropout on input (DL4J applies a layer's dropout to its input,
    #      BaseLayer.preOutputWithPreNorm -> Dropout.applyDropout) ----
    def apply_input_dropout(self, x, *, training, rng):
        if not training or self.dropout <= 0.0 or rng is None:
            return x
        keep = 1.0 - self.dropout
        m = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(m, x / keep, 0.0)

    # ---- serde ----
    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, tuple):
                v = list(v)
            d[f.name] = v
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Layer":
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in d:
                v = d[f.name]
                if isinstance(v, list):
                    v = tuple(tuple(e) if isinstance(e, list) else e for e in v)
                kw[f.name] = v
        return cls(**kw)


@dataclasses.dataclass
class BaseLayer(Layer):
    """Layers with weights (nn/conf/layers/BaseLayer.java): activation,
    weight init, L1/L2, per-layer updater overrides."""

    activation: str = "identity"
    weight_init: str = "xavier"
    weight_distribution: Optional[dict] = None
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    updater: Optional[dict] = None        # per-layer optimizer override
    bias_updater: Optional[dict] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0

    def activation_fn(self):
        return activations.get(self.activation)

    def _sample_w(self, key, shape, fan_in, fan_out):
        return init_weight(key, shape, self.weight_init, fan_in, fan_out,
                           distribution=self.weight_distribution,
                           dtype=dtypes.policy().param_dtype)

    def regularization_loss(self, params) -> jnp.ndarray:
        reg = jnp.zeros(())
        for k, p in params.items():
            is_bias = k == "b"
            l1 = self.l1_bias if is_bias else self.l1
            l2 = self.l2_bias if is_bias else self.l2
            if l1:
                reg = reg + l1 * jnp.sum(jnp.abs(p))
            if l2:
                # DL4J convention: 0.5 * l2 * ||w||^2
                reg = reg + 0.5 * l2 * jnp.sum(p * p)
        return reg


@dataclasses.dataclass
class FeedForwardLayer(BaseLayer):
    """Adds nIn/nOut geometry (nn/conf/layers/FeedForwardLayer.java)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    has_bias: bool = True

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            # direct initialize() must fail as loudly as the builder
            # path (which hits the same check via output_type) — not
            # with a TypeError from the weight sampler
            raise ValueError(f"{type(self).__name__} requires n_out")

    def output_type(self, input_type: InputType) -> InputType:
        if self.n_out is None:
            raise ValueError(f"{type(self).__name__} requires n_out")
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)
