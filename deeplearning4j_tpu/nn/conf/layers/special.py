"""Special layers: FrozenLayer, VariationalAutoencoder, Yolo2OutputLayer.

- FrozenLayer: nn/layers/FrozenLayer.java — wraps any layer; executors
  stop gradients through its params (here: ``lax.stop_gradient`` on the
  param subtree + exclusion from the optimizer, handled by the
  executor's trainable-mask).
- VariationalAutoencoder: nn/layers/variational/VariationalAutoencoder
  .java (1154 LoC) — MLP encoder → diagonal-Gaussian latent →
  MLP decoder → pluggable reconstruction distribution
  (nn/conf/layers/variational/*: Bernoulli/Gaussian/Exponential/
  Composite). Supervised forward = encoder mean activations (matching
  the reference's use as a feature extractor); unsupervised pretraining
  maximises the ELBO.
- Yolo2OutputLayer: nn/layers/objdetect/Yolo2OutputLayer.java (663 LoC)
  — YOLOv2 loss over anchor-box grid predictions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn import activations
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayer, FeedForwardLayer, Layer, register_layer, layer_from_dict,
)
from deeplearning4j_tpu.nn.weights import init_weight

__all__ = ["FrozenLayer", "VariationalAutoencoder", "Yolo2OutputLayer"]


@register_layer
@dataclasses.dataclass
class FrozenLayer(Layer):
    """Wraps a layer whose params receive no updates
    (nn/layers/FrozenLayer.java; created by transfer learning's
    setFeatureExtractor)."""

    inner: Optional[dict] = None

    def __post_init__(self):
        if isinstance(self.inner, Layer):
            self._inner = self.inner
            self.inner = self._inner.to_dict()
        elif self.inner is not None:
            self._inner = layer_from_dict(self.inner)
        else:
            self._inner = None

    @property
    def wrapped(self) -> Layer:
        return self._inner

    def set_n_in(self, input_type: InputType) -> None:
        self._inner.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return self._inner.output_type(input_type)

    def initialize(self, key, input_type: InputType):
        p, s = self._inner.initialize(key, input_type)
        self.inner = self._inner.to_dict()
        return p, s

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        frozen = jax.lax.stop_gradient(params)
        # frozen layers run in inference mode (reference FrozenLayer
        # disables dropout and training-time behavior)
        return self._inner.apply(frozen, state, x, training=False, rng=rng,
                                 mask=mask)

    def has_loss(self):
        return self._inner.has_loss()

    def to_dict(self) -> dict:
        return {"@type": "FrozenLayer", "name": self.name,
                "dropout": self.dropout, "inner": self.inner}


def _mlp_init(key, sizes, weight_init, pd):
    params = []
    keys = jax.random.split(key, len(sizes) - 1)
    for k, nin, nout in zip(keys, sizes[:-1], sizes[1:]):
        params.append({
            "W": init_weight(k, (nin, nout), weight_init, nin, nout, dtype=pd),
            "b": jnp.zeros((nout,), pd),
        })
    return params


def _mlp_apply(layers, x, act):
    for lay in layers:
        x = act(x @ lay["W"] + lay["b"])
    return x


@register_layer
@dataclasses.dataclass
class VariationalAutoencoder(FeedForwardLayer):
    """(nn/conf/layers/variational/VariationalAutoencoder.java).

    ``encoder_layer_sizes`` / ``decoder_layer_sizes`` mirror
    encoderLayerSizes/decoderLayerSizes; ``n_out`` is the latent size
    (nOut in the reference); ``reconstruction_distribution`` one of
    'bernoulli' | 'gaussian' | 'exponential', matching
    nn/conf/layers/variational/{Bernoulli,Gaussian,Exponential}
    ReconstructionDistribution. ``num_samples`` = MC samples for the
    ELBO (reference numSamples).
    """

    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    reconstruction_distribution: str = "bernoulli"
    pzx_activation: str = "identity"
    num_samples: int = 1
    activation: str = "tanh"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        pd = dtypes.policy().param_dtype
        k_enc, k_mu, k_lv, k_dec, k_out = jax.random.split(key, 5)
        enc_sizes = (self.n_in,) + tuple(self.encoder_layer_sizes)
        dec_sizes = (self.n_out,) + tuple(self.decoder_layer_sizes)
        eh = enc_sizes[-1]
        dh = dec_sizes[-1]
        # gaussian reconstruction needs mean+logvar per visible unit
        rec_out = (2 * self.n_in
                   if self.reconstruction_distribution == "gaussian"
                   else self.n_in)
        params = {
            "enc": _mlp_init(k_enc, enc_sizes, self.weight_init, pd),
            "mu": {"W": init_weight(k_mu, (eh, self.n_out), self.weight_init,
                                    eh, self.n_out, dtype=pd),
                   "b": jnp.zeros((self.n_out,), pd)},
            "logvar": {"W": init_weight(k_lv, (eh, self.n_out),
                                        self.weight_init, eh, self.n_out,
                                        dtype=pd),
                       "b": jnp.zeros((self.n_out,), pd)},
            "dec": _mlp_init(k_dec, dec_sizes, self.weight_init, pd),
            "out": {"W": init_weight(k_out, (dh, rec_out), self.weight_init,
                                     dh, rec_out, dtype=pd),
                    "b": jnp.zeros((rec_out,), pd)},
        }
        return params, {}

    def _encode(self, params, x):
        act = self.activation_fn()
        h = _mlp_apply(params["enc"], x, act)
        mu = h @ params["mu"]["W"] + params["mu"]["b"]
        logvar = h @ params["logvar"]["W"] + params["logvar"]["b"]
        return mu, logvar

    def _decode(self, params, z):
        act = self.activation_fn()
        h = _mlp_apply(params["dec"], z, act)
        return h @ params["out"]["W"] + params["out"]["b"]

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        mu, _ = self._encode(params, x)
        return activations.get(self.pzx_activation)(mu), state

    def _reconstruction_logprob(self, dec_out, x):
        d = self.reconstruction_distribution
        if d == "bernoulli":
            p = jax.nn.sigmoid(dec_out)
            eps = 1e-7
            return jnp.sum(x * jnp.log(p + eps)
                           + (1 - x) * jnp.log(1 - p + eps), axis=-1)
        if d == "gaussian":
            mean, logvar = jnp.split(dec_out, 2, axis=-1)
            var = jnp.exp(logvar)
            return jnp.sum(-0.5 * (jnp.log(2 * jnp.pi) + logvar
                                   + (x - mean) ** 2 / var), axis=-1)
        if d == "exponential":
            lam = jnp.exp(jnp.clip(dec_out, -20, 20))
            return jnp.sum(jnp.log(lam) - lam * x, axis=-1)
        raise ValueError(f"Unknown reconstruction distribution '{d}'")

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO (mean over batch), MC-estimated with
        ``num_samples`` draws — the quantity the reference minimises in
        VariationalAutoencoder.computeGradientAndScore."""
        # exp/log ELBO math must not run at bf16 activation precision
        # (promote_half: never downcasts the checker's f64)
        x = dtypes.promote_half(x)
        mu, logvar = self._encode(params, x)
        kl = -0.5 * jnp.sum(1 + logvar - mu ** 2 - jnp.exp(logvar), axis=-1)
        rec = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            rec = rec + self._reconstruction_logprob(self._decode(params, z),
                                                     x)
        rec = rec / self.num_samples
        return jnp.mean(kl - rec)

    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """reconstructionProbability (reference :  used for anomaly
        detection) — MC estimate of log p(x)."""
        mu, logvar = self._encode(params, x)
        keys = jax.random.split(rng, num_samples)
        logps = []
        for k in keys:
            eps = jax.random.normal(k, mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            logps.append(self._reconstruction_logprob(
                self._decode(params, z), x))
        return jax.nn.logsumexp(jnp.stack(logps), axis=0) - jnp.log(
            float(num_samples))

    def generate(self, params, z):
        """Decode latent samples to visible-space means
        (generateAtMeanGivenZ)."""
        dec_out = self._decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(dec_out)
        if self.reconstruction_distribution == "gaussian":
            return jnp.split(dec_out, 2, axis=-1)[0]
        return jnp.exp(jnp.clip(dec_out, -20, 20))


@register_layer
@dataclasses.dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 output layer (nn/conf/layers/objdetect/Yolo2OutputLayer
    .java + nn/layers/objdetect/Yolo2OutputLayer.java).

    Input: conv activations (B, H, W, A*(5+C)); labels: (B, H, W,
    A*(5+C))-formatted ground truth built by the data pipeline (or the
    (B, 4+label) box format converted upstream). ``anchors``: (A, 2)
    prior box sizes in grid units. Loss follows YOLOv2: coordinate SSE
    (lambda_coord, sqrt-w/h), object/no-object confidence SSE
    (IOU-weighted), class cross-entropy.
    """

    anchors: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def __post_init__(self):
        self.anchors = tuple(tuple(float(v) for v in a) for a in self.anchors)

    def has_loss(self):
        return True

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _split(self, y, n_classes):
        # y: (B,H,W,A,5+C) after reshape
        xy = jax.nn.sigmoid(y[..., 0:2])          # center offsets in cell
        wh = y[..., 2:4]                          # raw; exp * anchor
        conf = jax.nn.sigmoid(y[..., 4])
        cls = y[..., 5:]
        return xy, wh, conf, cls

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        b, h, w, c = x.shape
        a = len(self.anchors)
        depth = c // a
        y = x.reshape(b, h, w, a, depth)
        xy, wh, conf, cls = self._split(y, depth - 5)
        anchors = jnp.asarray(self.anchors)
        wh = jnp.exp(jnp.clip(wh, -10, 10)) * anchors
        out = jnp.concatenate(
            [xy, wh, conf[..., None], jax.nn.softmax(cls, axis=-1)], axis=-1)
        return out.reshape(b, h, w, c), state

    def loss_from_input(self, params, x, labels, *, training, rng, mask=None):
        # the YOLO loss does exp/sqrt/log_softmax — promote out of the
        # bf16 activation dtype before any of it (never downcasting
        # the gradient checker's f64)
        x = dtypes.promote_half(x)
        labels = dtypes.promote_half(labels)
        b, h, w, c = x.shape
        a = len(self.anchors)
        depth = c // a
        y = x.reshape(b, h, w, a, depth)
        t = labels.reshape(b, h, w, a, depth)
        xy, wh_raw, conf, cls = self._split(y, depth - 5)
        anchors = jnp.asarray(self.anchors)
        wh = jnp.exp(jnp.clip(wh_raw, -10, 10)) * anchors

        t_xy = t[..., 0:2]
        t_wh = t[..., 2:4]
        t_obj = t[..., 4]                          # 1 where object present
        t_cls = t[..., 5:]

        coord = jnp.sum(
            t_obj[..., None] * ((xy - t_xy) ** 2
                                + (jnp.sqrt(wh + 1e-8)
                                   - jnp.sqrt(t_wh + 1e-8)) ** 2),
            axis=-1)
        obj_loss = t_obj * (conf - 1.0) ** 2
        noobj_loss = (1.0 - t_obj) * conf ** 2
        logp = jax.nn.log_softmax(cls, axis=-1)
        cls_loss = -jnp.sum(t_cls * logp, axis=-1) * t_obj

        total = (self.lambda_coord * coord + obj_loss
                 + self.lambda_no_obj * noobj_loss + cls_loss)
        return jnp.mean(jnp.sum(total, axis=(1, 2, 3)))
