"""Pooling layers.

Replaces the reference's SubsamplingLayer
(nn/layers/convolution/subsampling/SubsamplingLayer.java) + its cuDNN
helper (CudnnSubsamplingHelper.java) with ``lax.reduce_window`` — XLA
fuses and schedules these natively on TPU. GlobalPoolingLayer mirrors
nn/layers/pooling/GlobalPoolingLayer.java incl. masked time-series
pooling (MaskedReductionUtil semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.conf.layers.convolutional import _pair, _out_dim

__all__ = ["PoolingType", "SubsamplingLayer", "Subsampling1DLayer",
           "GlobalPoolingLayer"]


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_layer
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """2-d pooling (nn/conf/layers/SubsamplingLayer.java)."""

    pooling: str = PoolingType.MAX
    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def __post_init__(self):
        self.kernel = _pair(self.kernel)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)

    def output_type(self, input_type: InputType) -> InputType:
        h = _out_dim(input_type.height, self.kernel[0], self.stride[0],
                     self.padding[0], self.convolution_mode)
        w = _out_dim(input_type.width, self.kernel[1], self.stride[1],
                     self.padding[1], self.convolution_mode)
        return InputType.convolutional(h, w, input_type.channels)

    def _window_pool(self, x):
        window = (1,) + self.kernel + (1,)
        strides = (1,) + self.stride + (1,)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = ((0, 0), (self.padding[0], self.padding[0]),
                   (self.padding[1], self.padding[1]), (0, 0))
        if self.pooling == PoolingType.MAX:
            return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                     pad)
        if self.pooling in (PoolingType.AVG, PoolingType.SUM):
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            if self.pooling == PoolingType.SUM:
                return s
            if self.convolution_mode == "same":
                ones = jnp.ones_like(x)
                counts = lax.reduce_window(ones, 0.0, lax.add, window,
                                           strides, pad)
                return s / counts
            return s / (self.kernel[0] * self.kernel[1])
        if self.pooling == PoolingType.PNORM:
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window,
                                  strides, pad)
            return s ** (1.0 / p)
        raise ValueError(f"Unknown pooling type {self.pooling}")

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        return self._window_pool(x), state


@register_layer
@dataclasses.dataclass
class Subsampling1DLayer(SubsamplingLayer):
    """1-d pooling over (B,T,C) (nn/conf/layers/Subsampling1DLayer.java)."""

    def __post_init__(self):
        k = self.kernel[0] if isinstance(self.kernel, (tuple, list)) \
            else self.kernel
        s = self.stride[0] if isinstance(self.stride, (tuple, list)) \
            else self.stride
        p = self.padding[0] if isinstance(self.padding, (tuple, list)) \
            else self.padding
        self.kernel = (int(k), 1)
        self.stride = (int(s), 1)
        self.padding = (int(p), 0)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = _out_dim(t, self.kernel[0], self.stride[0], self.padding[0],
                         self.convolution_mode)
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        y = self._window_pool(x[:, :, None, :])[:, :, 0, :]
        return y, state


@register_layer
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial (CNN) or time (RNN) dims
    (nn/conf/layers/GlobalPoolingLayer.java). Respects sequence masks
    the way MaskedReductionUtil does: masked steps excluded from
    max/avg/sum."""

    pooling: str = PoolingType.AVG
    pnorm: int = 2
    collapse_dimensions: bool = True

    # under sequence parallelism this layer COLLAPSES the sharded time
    # axis with a collective (pmax/psum/pmean over the seq axis), so
    # downstream layers see replicated activations — the wrapper's
    # validation lets any layer follow it (Layer base declares False)
    seq_collapses_time = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return input_type

    @staticmethod
    def _combine(val, seq_ax, op):
        """Combine local pools across the seq axis, then re-mark the
        (now identical-everywhere) result as device-varying: the seq
        step's loss pmean and the /nshards gradient normalization
        count one term per shard, so the collective's output must
        keep the varying type (each shard's identical copy IS its
        term)."""
        if not seq_ax:
            return val
        if op is lax.pmax:
            # pmax has no differentiation rule: gather + max instead
            # (gradient flows to the argmax shard's local pool); the
            # gathered result already carries the varying type
            return jnp.max(lax.all_gather(val, seq_ax), axis=0)
        # psum/pmean outputs are seq-INVARIANT: re-mark varying
        # (identity on jax 0.4.x — no varying-axes types there; the
        # wrapper's shard_map runs check_rep=False, parallel/compat.py)
        from deeplearning4j_tpu.parallel.compat import pcast_varying
        return pcast_varying(op(val, seq_ax), seq_ax)

    def apply_stream(self, params, cache, x):
        """Stateful streaming inference (the rnnTimeStep contract
        extended through the time collapse): the carry is the running
        pool statistic — sum+count (avg), max, sum, or Σ|x|^p
        (pnorm). Each step returns the pool over the stream SO FAR,
        so the final step equals the full-sequence ``apply`` and a
        prefix step is the prediction on that prefix."""
        if x.ndim != 3:
            raise ValueError("apply_stream pools over TIME: input "
                             f"must be (B, t, C), got {x.shape}")
        if self.pooling == PoolingType.MAX:
            cur = jnp.max(x, axis=1)
            m = cur if cache is None else jnp.maximum(cache, cur)
            return m, m
        if self.pooling in (PoolingType.AVG, PoolingType.SUM):
            s_new = jnp.sum(x, axis=1)
            n_new = x.shape[1]
            if cache is not None:
                s_new = s_new + cache["sum"]
                n_new = n_new + cache["count"]
            cache = {"sum": s_new, "count": n_new}
            if self.pooling == PoolingType.SUM:
                return s_new, cache
            return s_new / n_new, cache
        if self.pooling == PoolingType.PNORM:
            p = float(self.pnorm)
            s_new = jnp.sum(jnp.abs(x) ** p, axis=1)
            if cache is not None:
                s_new = s_new + cache
            return s_new ** (1.0 / p), s_new
        raise ValueError(self.pooling)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        from deeplearning4j_tpu.parallel.seq_context import (
            current_seq_axis)
        if x.ndim == 4:          # NHWC → pool over H,W
            axes = (1, 2)
            seq_ax = None
        elif x.ndim == 3:        # NTC → pool over T
            axes = (1,)
            # sequence-parallel: x is the LOCAL time chunk — pool
            # locally, then combine across the seq axis so every
            # shard holds the GLOBAL pool (replicated downstream)
            seq_ax = current_seq_axis()
        else:
            return x, state
        if mask is not None and x.ndim == 3:
            m = mask[..., None]          # (B,T,1)
            if self.pooling == PoolingType.MAX:
                big_neg = jnp.finfo(x.dtype).min
                out = jnp.max(jnp.where(m > 0, x, big_neg), axis=1)
                return self._combine(out, seq_ax, lax.pmax), state
            if self.pooling == PoolingType.SUM:
                out = jnp.sum(x * m, axis=1)
                return self._combine(out, seq_ax, lax.psum), state
            if self.pooling == PoolingType.AVG:
                # global masked mean: combine numerator AND count
                num = self._combine(jnp.sum(x * m, axis=1), seq_ax,
                                    lax.psum)
                den = self._combine(jnp.sum(m, axis=1), seq_ax,
                                    lax.psum)
                return num / jnp.maximum(den, 1.0), state
            if self.pooling == PoolingType.PNORM:
                p = float(self.pnorm)
                s = jnp.sum((jnp.abs(x) * m) ** p, axis=1)
                s = self._combine(s, seq_ax, lax.psum)
                return s ** (1.0 / p), state
        if self.pooling == PoolingType.MAX:
            out = jnp.max(x, axis=axes)
            return self._combine(out, seq_ax, lax.pmax), state
        if self.pooling == PoolingType.AVG:
            out = jnp.mean(x, axis=axes)     # equal chunks: pmean exact
            return self._combine(out, seq_ax, lax.pmean), state
        if self.pooling == PoolingType.SUM:
            out = jnp.sum(x, axis=axes)
            return self._combine(out, seq_ax, lax.psum), state
        if self.pooling == PoolingType.PNORM:
            p = float(self.pnorm)
            s = jnp.sum(jnp.abs(x) ** p, axis=axes)
            s = self._combine(s, seq_ax, lax.psum)
            return s ** (1.0 / p), state
        raise ValueError(self.pooling)
