"""Layer configuration classes.

One config class per reference layer type (nn/conf/layers/*.java, 32
files). Unlike the reference — where a config class and a separate
impl class exist per layer (nn/conf/layers/DenseLayer.java vs
nn/layers/feedforward/dense/DenseLayer.java) — each config here *owns*
its functional implementation: ``initialize`` builds the param/state
pytrees and ``apply`` is the pure forward function. Backprop is
``jax.grad`` of the composed network; there is no per-layer
``backpropGradient``.
"""

from deeplearning4j_tpu.nn.conf.layers.base import (
    Layer, BaseLayer, FeedForwardLayer, register_layer, layer_from_dict,
)
from deeplearning4j_tpu.nn.conf.layers.core import (
    DenseLayer, ActivationLayer, DropoutLayer, EmbeddingLayer,
    EmbeddingSequenceLayer, AutoEncoder, RBM, RecursiveAutoEncoder,
)
from deeplearning4j_tpu.nn.conf.layers.output import (
    OutputLayer, RnnOutputLayer, LossLayer, CenterLossOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.convolutional import (
    ConvolutionLayer, Convolution1DLayer, Deconvolution2DLayer,
    SeparableConvolution2DLayer, DepthwiseConvolution2DLayer,
    ZeroPaddingLayer, ZeroPadding1DLayer, UpsamplingLayer, CroppingLayer,
    SpaceToDepthLayer, SpaceToBatchLayer,
)
from deeplearning4j_tpu.nn.conf.layers.pooling import (
    SubsamplingLayer, Subsampling1DLayer, GlobalPoolingLayer, PoolingType,
)
from deeplearning4j_tpu.nn.conf.layers.normalization import (
    BatchNormalization, LayerNormalization,
    LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    LSTM, GravesLSTM, GravesBidirectionalLSTM, Bidirectional, SimpleRnn,
    LastTimeStep, RnnLossLayer,
)
from deeplearning4j_tpu.nn.conf.layers.special import (
    FrozenLayer, VariationalAutoencoder, Yolo2OutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.attention import (
    SelfAttentionLayer, TransformerEncoderLayer,
)

__all__ = [
    "Layer", "BaseLayer", "FeedForwardLayer", "register_layer",
    "layer_from_dict",
    "DenseLayer", "ActivationLayer", "DropoutLayer", "EmbeddingLayer",
    "EmbeddingSequenceLayer", "AutoEncoder", "RBM", "RecursiveAutoEncoder",
    "OutputLayer", "RnnOutputLayer", "LossLayer", "CenterLossOutputLayer",
    "ConvolutionLayer", "Convolution1DLayer", "Deconvolution2DLayer",
    "SeparableConvolution2DLayer", "DepthwiseConvolution2DLayer",
    "ZeroPaddingLayer", "ZeroPadding1DLayer", "UpsamplingLayer",
    "CroppingLayer", "SpaceToDepthLayer", "SpaceToBatchLayer",
    "SubsamplingLayer", "Subsampling1DLayer", "GlobalPoolingLayer",
    "PoolingType",
    "BatchNormalization", "LayerNormalization",
    "LocalResponseNormalization",
    "LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "Bidirectional",
    "SimpleRnn", "LastTimeStep", "RnnLossLayer",
    "FrozenLayer", "VariationalAutoencoder", "Yolo2OutputLayer",
    "SelfAttentionLayer", "TransformerEncoderLayer",
]
