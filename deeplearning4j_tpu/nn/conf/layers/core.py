"""Core feed-forward layers: Dense, Activation, Dropout, Embedding,
AutoEncoder.

Reference: nn/conf/layers/DenseLayer.java + nn/layers/feedforward/**.
Dense on an RNN input applies time-distributed (the reference routes
through an RnnToFeedForwardPreProcessor; here a 3-d input just works —
the matmul contracts the last axis).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayer, BaseLayer, Layer, register_layer,
)

__all__ = ["DenseLayer", "ActivationLayer", "DropoutLayer",
           "EmbeddingLayer", "EmbeddingSequenceLayer", "AutoEncoder",
           "RBM"]


@register_layer
@dataclasses.dataclass
class DenseLayer(FeedForwardLayer):
    """Fully connected layer (reference nn/conf/layers/DenseLayer.java,
    impl nn/layers/feedforward/dense/DenseLayer.java)."""

    # on (B,T,C) recurrent input the matmul is per-timestep
    seq_parallelizable = True

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        p = {"W": self._sample_w(key, (self.n_in, self.n_out),
                                 self.n_in, self.n_out)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        if x.ndim > 2 and x.shape[-1] != params["W"].shape[0]:
            x = x.reshape(x.shape[0], -1)   # cnn -> flatten
        # MXU-native compute dtype (no-op casts under the f32 default)
        pol = dtypes.policy()
        y = pol.cast_to_output(
            pol.cast_to_compute(x) @ pol.cast_to_compute(params["W"]))
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state

    def output_type(self, input_type: InputType) -> InputType:
        if self.n_out is None:
            raise ValueError("DenseLayer requires n_out")
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timesteps)
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclasses.dataclass
class ActivationLayer(BaseLayer):
    """Activation-only layer (nn/conf/layers/ActivationLayer.java)."""

    seq_parallelizable = True          # elementwise

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        return self.activation_fn()(x), state


@register_layer
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (nn/conf/layers/DropoutLayer.java). Identity at
    inference; inverted-dropout scaling at train time."""

    seq_parallelizable = True          # elementwise

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        return self.apply_input_dropout(x, training=training, rng=rng), state


@register_layer
@dataclasses.dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index → vector lookup (nn/conf/layers/EmbeddingLayer.java, impl
    nn/layers/feedforward/embedding/EmbeddingLayer.java). Input: int ids
    of shape (B,) or (B,1); a one-hot-equivalent gather — MXU-friendly
    when XLA lowers to take()."""

    def initialize(self, key, input_type: InputType):
        if self.n_in is None:
            self.n_in = input_type.flat_size()
        p = {"W": self._sample_w(key, (self.n_in, self.n_out),
                                 self.n_in, self.n_out)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclasses.dataclass
class EmbeddingSequenceLayer(FeedForwardLayer):
    """Sequence of ids (B,T) → (B,T,n_out) (reference added this in
    later versions; capability parity with Keras Embedding import)."""

    seq_parallelizable = True          # per-token gather

    def initialize(self, key, input_type: InputType):
        if self.n_in is None:
            self.n_in = input_type.size
        return {"W": self._sample_w(key, (self.n_in, self.n_out),
                                    self.n_in, self.n_out)}, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return jnp.take(params["W"], idx, axis=0), state

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)


@register_layer
@dataclasses.dataclass
class RBM(FeedForwardLayer):
    """Restricted Boltzmann Machine (nn/conf/layers/RBM.java, impl
    nn/layers/feedforward/rbm/RBM.java — the reference's legacy
    pretraining layer).

    Supervised forward = hidden activations (sigmoid propup), like the
    reference. Unsupervised pretraining uses contrastive divergence:
    ``pretrain_loss`` is the free-energy difference F(v) − F(ṽ) with
    the CD-1 reconstruction ṽ held constant (stop_gradient), whose
    gradient is exactly the CD-1 update — so the same jitted
    pretraining machinery (jax.grad + optax) that serves AutoEncoder/VAE
    drives RBM, instead of the reference's hand-coded Gibbs updates.
    """

    k: int = 1                      # CD-k Gibbs steps
    activation: str = "sigmoid"
    visible_unit: str = "binary"    # 'binary' | 'gaussian'
    hidden_unit: str = "binary"

    def __post_init__(self):
        # the softplus free-energy form assumes sigmoid-binary hiddens;
        # reject configs that would silently train a different model
        if self.activation != "sigmoid":
            raise ValueError("RBM supports only sigmoid hidden "
                             "activation (free-energy objective)")
        if self.visible_unit not in ("binary", "gaussian"):
            raise ValueError(f"RBM visible_unit must be 'binary' or "
                             f"'gaussian', got '{self.visible_unit}'")
        # the softplus marginalization below is the BINARY-hidden free
        # energy; gaussian hiddens need a quadratic term we don't
        # implement — reject rather than silently fit the wrong model
        if self.hidden_unit != "binary":
            raise ValueError(f"RBM hidden_unit supports only 'binary', "
                             f"got '{self.hidden_unit}'")

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        pd = dtypes.policy().param_dtype
        return {
            "W": self._sample_w(key, (self.n_in, self.n_out),
                                self.n_in, self.n_out),
            "b": jnp.full((self.n_out,), self.bias_init, pd),  # hidden
            "vb": jnp.zeros((self.n_in,), pd),                 # visible
        }, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        return jax.nn.sigmoid(x @ params["W"] + params["b"]), state

    def _free_energy(self, params, v):
        # F(v) = -v·vb - Σ softplus(vW + hb)
        vis = jnp.sum(v * params["vb"], axis=-1)
        hid = jnp.sum(jax.nn.softplus(v @ params["W"] + params["b"]),
                      axis=-1)
        return -vis - hid

    def _gibbs(self, params, v, rng):
        ph = jax.nn.sigmoid(v @ params["W"] + params["b"])
        k1, _ = jax.random.split(rng)
        h = jax.random.bernoulli(k1, ph).astype(v.dtype)
        pv = h @ params["W"].T + params["vb"]
        if self.visible_unit == "binary":
            pv = jax.nn.sigmoid(pv)
        return pv

    def pretrain_loss(self, params, x, rng):
        v_model = x
        keys = jax.random.split(rng, max(self.k, 1))
        for kk in keys:
            v_model = self._gibbs(params, v_model, kk)
        v_model = jax.lax.stop_gradient(v_model)
        return jnp.mean(self._free_energy(params, x)
                        - self._free_energy(params, v_model))

    def reconstruction_error(self, params, x, rng):
        recon = self._gibbs(params, x, rng)
        return jnp.mean((x - recon) ** 2)


@register_layer
@dataclasses.dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder layer (nn/conf/layers/AutoEncoder.java,
    impl nn/layers/feedforward/autoencoder/AutoEncoder.java).

    Supervised forward = encode only. Unsupervised pretraining
    (corrupt → encode → decode → reconstruction loss) is exposed via
    ``pretrain_loss`` and driven by MultiLayerNetwork.pretrain, the
    analog of BasePretrainNetwork.
    """

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        k1, k2 = jax.random.split(key)
        pd = dtypes.policy().param_dtype
        return {
            "W": self._sample_w(k1, (self.n_in, self.n_out),
                                self.n_in, self.n_out),
            "b": jnp.full((self.n_out,), self.bias_init, pd),
            "vb": jnp.zeros((self.n_in,), pd),     # visible bias (decode)
        }, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        return self.activation_fn()(x @ params["W"] + params["b"]), state

    def pretrain_loss(self, params, x, rng):
        from deeplearning4j_tpu.nn import losses as losses_mod
        act = self.activation_fn()
        if self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level,
                                        x.shape)
            xc = jnp.where(keep, x, 0.0)
        else:
            xc = x
        h = act(xc @ params["W"] + params["b"])
        recon = act(h @ params["W"].T + params["vb"])
        return jnp.mean(losses_mod.get(self.loss)(x, recon, None))


@register_layer
@dataclasses.dataclass
class RecursiveAutoEncoder(FeedForwardLayer):
    """Recursive autoencoder over sequences
    (nn/conf/layers/RecursiveAutoEncoder... — reference impl
    nn/layers/feedforward/recursive/RecursiveAutoEncoder.java): the
    hidden code folds the sequence left to right — at each step the
    carry and the next input are jointly encoded, and pretraining
    reconstructs the [carry; input] pair from the code. TPU-native
    shape: the fold is a ``lax.scan`` (sequential by definition; the
    matmuls inside still batch over B on the MXU).

    Supervised forward = the final code (B, n_out) — a
    sequence-collapsing encoder. ``pretrain_loss`` = mean
    reconstruction error across steps, driven by
    MultiLayerNetwork.pretrain like the other BasePretrainNetwork
    analogs (AutoEncoder/RBM/VAE).
    """

    loss: str = "mse"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def set_n_in(self, input_type: InputType) -> None:
        self.n_in = input_type.size

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        k1, k2 = jax.random.split(key)
        pd = dtypes.policy().param_dtype
        z = self.n_out + self.n_in          # [carry; x_t]
        return {
            "W": self._sample_w(k1, (z, self.n_out), z, self.n_out),
            "b": jnp.full((self.n_out,), self.bias_init, pd),
            "Wd": self._sample_w(k2, (self.n_out, z), self.n_out, z),
            "vb": jnp.zeros((z,), pd),       # decode bias
        }, {}

    def _fold(self, params, x, mask=None):
        """x: (B, T, C) → (final code (B, n_out), mean recon loss).
        ``mask`` (B, T) 0/1: padded steps neither advance the carry
        nor contribute reconstruction loss (same state-gating contract
        as the recurrent layers)."""
        from deeplearning4j_tpu.nn import losses as losses_mod
        act = self.activation_fn()
        loss_fn = losses_mod.get(self.loss)
        B = x.shape[0]
        h0 = jnp.zeros((B, self.n_out), x.dtype)
        if mask is None:
            m_t = jnp.ones((x.shape[1], B), x.dtype)
        else:
            m_t = jnp.swapaxes(jnp.asarray(mask, x.dtype), 0, 1)

        def step(h, inp):
            xt, mt = inp
            z = jnp.concatenate([h, xt], axis=-1)
            code = act(z @ params["W"] + params["b"])
            recon = act(code @ params["Wd"] + params["vb"])
            h_new = jnp.where(mt[:, None] > 0, code, h)
            per_ex = jnp.mean(loss_fn(z, recon, None).reshape(B, -1),
                              axis=-1)
            return h_new, (jnp.sum(per_ex * mt), jnp.sum(mt))

        h, (lsum, msum) = jax.lax.scan(step, h0,
                                       (jnp.swapaxes(x, 0, 1), m_t))
        mean_loss = jnp.sum(lsum) / jnp.maximum(jnp.sum(msum), 1.0)
        return h, mean_loss

    def apply(self, params, state, x, *, training=False, rng=None,
              mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        h, _ = self._fold(params, x, mask)
        return h, state

    def pretrain_loss(self, params, x, rng, mask=None):
        _, mean_loss = self._fold(params, x, mask)
        return mean_loss
