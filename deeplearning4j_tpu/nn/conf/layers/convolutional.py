"""Convolutional layers — NHWC, lowered to XLA conv_general_dilated.

Replaces the reference's im2col-GEMM path
(nn/layers/convolution/ConvolutionLayer.java:52) AND its cuDNN helper
(deeplearning4j-cuda CudnnConvolutionHelper.java:54): on TPU, XLA tiles
``lax.conv_general_dilated`` directly onto the MXU, so there is no
helper SPI — the compiler *is* the helper. Kernel layout is HWIO.

Padding modes mirror ConvolutionMode (nn/conf/ConvolutionMode.java):
'truncate' (valid-with-explicit-pad, DL4J default), 'same'.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayer, Layer, register_layer,
)

__all__ = ["ConvolutionLayer", "Convolution1DLayer", "Deconvolution2DLayer",
           "SeparableConvolution2DLayer", "DepthwiseConvolution2DLayer",
           "ZeroPaddingLayer", "ZeroPadding1DLayer", "UpsamplingLayer",
           "CroppingLayer", "SpaceToDepthLayer", "SpaceToBatchLayer"]


def _pair(v):
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _out_dim(size, k, s, p, mode, dilation=1):
    keff = k + (k - 1) * (dilation - 1)
    if mode == "same":
        return -(-size // s)
    return (size + 2 * p - keff) // s + 1


def _conv_padding(mode, pad, kernel, dilation=(1, 1)):
    if mode == "same":
        return "SAME"
    return [(p, p) for p in pad]


@register_layer
@dataclasses.dataclass
class ConvolutionLayer(BaseLayer):
    """2-d convolution (nn/conf/layers/ConvolutionLayer.java)."""

    n_in: Optional[int] = None        # channels in (inferred)
    n_out: Optional[int] = None       # filters
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"
    has_bias: bool = True
    activation: str = "identity"

    def __post_init__(self):
        self.kernel = _pair(self.kernel)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)
        self.dilation = _pair(self.dilation)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind not in ("cnn", "cnnflat"):
            raise ValueError(f"ConvolutionLayer needs CNN input, got "
                             f"{input_type}")
        h = _out_dim(input_type.height, self.kernel[0], self.stride[0],
                     self.padding[0], self.convolution_mode, self.dilation[0])
        w = _out_dim(input_type.width, self.kernel[1], self.stride[1],
                     self.padding[1], self.convolution_mode, self.dilation[1])
        return InputType.convolutional(h, w, self.n_out)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        kh, kw = self.kernel
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        p = {"W": self._sample_w(key, (kh, kw, self.n_in, self.n_out),
                                 fan_in, fan_out)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def _conv(self, x, w):
        # compute-dtype in, cast out AFTER the conv: upcasting via
        # preferred_element_type breaks the conv transpose under bf16
        # (f32 cotangent vs bf16 saved operands); an explicit convert
        # has a clean transpose and XLA's MXU path still accumulates
        # in f32 internally
        pol = dtypes.policy()
        y = lax.conv_general_dilated(
            pol.cast_to_compute(x), pol.cast_to_compute(w),
            window_strides=self.stride,
            padding=_conv_padding(self.convolution_mode, self.padding,
                                  self.kernel, self.dilation),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return pol.cast_to_output(y)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        y = self._conv(x, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer
@dataclasses.dataclass
class Convolution1DLayer(ConvolutionLayer):
    """1-d convolution over sequences (nn/conf/layers/Convolution1DLayer
    .java). Input (B,T,C) treated as width-1 2-d conv on time axis."""

    kernel: Tuple[int, int] = (3, 1)

    def __post_init__(self):
        k = self.kernel[0] if isinstance(self.kernel, (tuple, list)) \
            else self.kernel
        s = self.stride[0] if isinstance(self.stride, (tuple, list)) \
            else self.stride
        p = self.padding[0] if isinstance(self.padding, (tuple, list)) \
            else self.padding
        d = self.dilation[0] if isinstance(self.dilation, (tuple, list)) \
            else self.dilation
        self.kernel = (int(k), 1)
        self.stride = (int(s), 1)
        self.padding = (int(p), 0)
        self.dilation = (int(d), 1)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        if t is not None:
            t = _out_dim(t, self.kernel[0], self.stride[0], self.padding[0],
                         self.convolution_mode, self.dilation[0])
        return InputType.recurrent(self.n_out, t)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        y = self._conv(x[:, :, None, :], params["W"])[:, :, 0, :]
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer
@dataclasses.dataclass
class Deconvolution2DLayer(ConvolutionLayer):
    """Transposed convolution (capability parity with later-DL4J
    Deconvolution2D; Keras Conv2DTranspose import target)."""

    def output_type(self, input_type: InputType) -> InputType:
        def _od(size, k, s, p):
            if self.convolution_mode == "same":
                return size * s
            return s * (size - 1) + k - 2 * p
        h = _od(input_type.height, self.kernel[0], self.stride[0],
                self.padding[0])
        w = _od(input_type.width, self.kernel[1], self.stride[1],
                self.padding[1])
        return InputType.convolutional(h, w, self.n_out)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        kh, kw = self.kernel
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        p = {"W": self._sample_w(key, (kh, kw, self.n_out, self.n_in),
                                 fan_in, fan_out)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            # forward-conv-equivalent semantics: out = s*(in-1)+k-2p,
            # i.e. VALID transpose cropped by p per side (explicit pad
            # lists mean something else to lax.conv_transpose)
            pad = "VALID"
        y = lax.conv_transpose(
            x, params["W"], strides=self.stride, padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            transpose_kernel=True)
        if self.convolution_mode != "same" and any(self.padding):
            ph, pw = self.padding
            h, w = y.shape[1], y.shape[2]
            y = y[:, ph:h - ph or None, pw:w - pw or None, :]
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer
@dataclasses.dataclass
class DepthwiseConvolution2DLayer(ConvolutionLayer):
    """Depthwise conv (Keras DepthwiseConv2D target)."""

    depth_multiplier: int = 1

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.channels
        self.n_out = self.n_in * self.depth_multiplier

    def output_type(self, input_type: InputType) -> InputType:
        base = super().output_type(input_type)
        return InputType.convolutional(base.height, base.width,
                                       self.n_in * self.depth_multiplier)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        self.n_out = self.n_in * self.depth_multiplier
        kh, kw = self.kernel
        p = {"W": self._sample_w(key, (kh, kw, 1, self.n_out),
                                 kh * kw, kh * kw * self.depth_multiplier)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride,
            padding=_conv_padding(self.convolution_mode, self.padding,
                                  self.kernel),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in)
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer
@dataclasses.dataclass
class SeparableConvolution2DLayer(ConvolutionLayer):
    """Depthwise-separable conv (reference SeparableConvolution2D /
    Keras SeparableConv2D): depthwise then 1x1 pointwise."""

    depth_multiplier: int = 1

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        kd, kp = jax.random.split(key)
        kh, kw = self.kernel
        mult = self.depth_multiplier
        p = {
            "dW": self._sample_w(kd, (kh, kw, 1, self.n_in * mult),
                                 kh * kw, kh * kw * mult),
            "pW": self._sample_w(kp, (1, 1, self.n_in * mult, self.n_out),
                                 self.n_in * mult, self.n_out),
        }
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        y = lax.conv_general_dilated(
            x, params["dW"], window_strides=self.stride,
            padding=_conv_padding(self.convolution_mode, self.padding,
                                  self.kernel),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_in)
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return self.activation_fn()(y), state


@register_layer
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """(nn/conf/layers/ZeroPaddingLayer.java). pad = ((top,bottom),
    (left,right)) or a single int."""

    pad: Tuple[Tuple[int, int], Tuple[int, int]] = ((0, 0), (0, 0))

    def __post_init__(self):
        p = self.pad
        if isinstance(p, int):
            self.pad = ((p, p), (p, p))
        elif len(p) == 2 and all(isinstance(e, int) for e in p):
            self.pad = ((p[0], p[0]), (p[1], p[1]))
        else:
            self.pad = tuple(tuple(int(x) for x in e) for e in p)

    def output_type(self, input_type: InputType) -> InputType:
        (t, b), (l, r) = self.pad
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        (t, b), (l, r) = self.pad
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer
@dataclasses.dataclass
class ZeroPadding1DLayer(Layer):
    """(nn/conf/layers/ZeroPadding1DLayer.java)."""

    pad: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        if isinstance(self.pad, int):
            self.pad = (self.pad, self.pad)
        else:
            self.pad = tuple(int(x) for x in self.pad)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timesteps
        return InputType.recurrent(
            input_type.size,
            None if t is None else t + self.pad[0] + self.pad[1])

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        return jnp.pad(x, ((0, 0), self.pad, (0, 0))), state


@register_layer
@dataclasses.dataclass
class UpsamplingLayer(Layer):
    """Nearest-neighbor 2-d upsampling (reference Upsampling2D)."""

    size: Tuple[int, int] = (2, 2)

    def __post_init__(self):
        self.size = _pair(self.size)

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1),
                       self.size[1], axis=2)
        return y, state


@register_layer
@dataclasses.dataclass
class CroppingLayer(Layer):
    """2-d cropping (reference Cropping2D)."""

    crop: Tuple[Tuple[int, int], Tuple[int, int]] = ((0, 0), (0, 0))

    def __post_init__(self):
        c = self.crop
        if isinstance(c, int):
            self.crop = ((c, c), (c, c))
        elif len(c) == 2 and all(isinstance(e, int) for e in c):
            self.crop = ((c[0], c[0]), (c[1], c[1]))
        else:
            self.crop = tuple(tuple(int(x) for x in e) for e in c)

    def output_type(self, input_type: InputType) -> InputType:
        (t, b), (l, r) = self.crop
        return InputType.convolutional(input_type.height - t - b,
                                       input_type.width - l - r,
                                       input_type.channels)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        (t, b), (l, r) = self.crop
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state


@register_layer
@dataclasses.dataclass
class SpaceToDepthLayer(Layer):
    """(reference SpaceToDepthLayer; used by YOLO9000-style nets)."""

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.block_size
        return InputType.convolutional(input_type.height // b,
                                       input_type.width // b,
                                       input_type.channels * b * b)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                  b * b * c)
        return y, state


@register_layer
@dataclasses.dataclass
class SpaceToBatchLayer(Layer):
    """(reference SpaceToBatchLayer)."""

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.block_size
        return InputType.convolutional(input_type.height // b,
                                       input_type.width // b,
                                       input_type.channels)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        n, h, w, c = x.shape
        b = self.block_size
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = y.transpose(2, 4, 0, 1, 3, 5).reshape(n * b * b, h // b,
                                                  w // b, c)
        return y, state
