"""Attention layers — transformer building blocks.

The 2017 reference predates transformers (its long-context story is
tBPTT + masking, SURVEY §5); attention layers are a required capability
extension of the TPU rebuild. ``SelfAttentionLayer`` is multi-head
self-attention over (B,T,C) inputs backed by the Pallas flash kernel on
TPU (ops/attention.py — the framework's hand-written-kernel seam);
``TransformerEncoderLayer`` is the full pre-LN block (MHA + MLP with
residuals) so the config DSL can express transformer stacks.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (BaseLayer,
                                                    register_layer)

__all__ = ["SelfAttentionLayer", "TransformerEncoderLayer"]


from deeplearning4j_tpu.nn.conf.layers.normalization import (
    layer_norm as _layer_norm)


@register_layer
@dataclasses.dataclass
class SelfAttentionLayer(BaseLayer):
    """Multi-head self-attention, (B,T,C) → (B,T,n_out)."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None        # model dim (defaults to n_in)
    n_heads: int = 4
    causal: bool = False
    # biases on the q/k/v projections (Keras MultiHeadAttention
    # default; our native transformer blocks keep them off)
    qkv_bias: bool = False
    # bias on the output projection. Kept separate from qkv_bias so a
    # Keras MultiHeadAttention(use_bias=False) import has the SAME
    # trainable surface as the source model — a zero-initialized bo
    # matches at inference but would train a parameter Keras doesn't
    # have (ADVICE r4)
    out_bias: bool = True

    seq_parallelizable = True          # attention rides the ring

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.size
        if self.n_out is None:
            self.n_out = self.n_in

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out or input_type.size,
                                   input_type.timesteps)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out {self.n_out} not divisible by "
                             f"n_heads {self.n_heads}")
        kq, kk, kv, ko = jax.random.split(key, 4)
        pd = dtypes.policy().param_dtype
        d = self.n_out
        p = {
            "Wq": self._sample_w(kq, (self.n_in, d), self.n_in, d),
            "Wk": self._sample_w(kk, (self.n_in, d), self.n_in, d),
            "Wv": self._sample_w(kv, (self.n_in, d), self.n_in, d),
            "Wo": self._sample_w(ko, (d, d), d, d),
        }
        if self.out_bias:
            p["bo"] = jnp.zeros((d,), pd)
        if self.qkv_bias:
            p["bq"] = jnp.zeros((d,), pd)
            p["bk"] = jnp.zeros((d,), pd)
            p["bv"] = jnp.zeros((d,), pd)
        return p, {}

    def apply(self, params, state, x, *, training=False, rng=None,
              mask=None):
        from deeplearning4j_tpu.ops.attention import flash_attention
        x = self.apply_input_dropout(x, training=training, rng=rng)
        B, T, _ = x.shape
        q, k, v = self._project_qkv(params, x)
        from deeplearning4j_tpu.parallel.seq_context import (
            current_seq_axis, current_seq_mesh)
        seq_axis = current_seq_axis()
        seq_mesh = current_seq_mesh()
        if seq_axis is not None and seq_mesh is not None:
            # GSPMD-mode sequence parallelism (seq composed with
            # dp/tp): the step is a plain jit, so the ring gets its
            # own shard_map ISLAND over just the seq axis — other
            # mesh axes (data, model) stay automatic, which is what
            # lets Megatron head-sharded projections compose with the
            # ring (seq_context.current_seq_mesh docstring).
            from jax.sharding import PartitionSpec as _P

            from deeplearning4j_tpu.parallel.ring_attention import (
                ring_self_attention)
            try:
                from jax import shard_map as _shard_map
            except ImportError:
                # the legacy jax.experimental.shard_map has no
                # partial-manual (axis_names=) mode, so the island
                # cannot be expressed there — no silent fallback
                raise RuntimeError(
                    "GSPMD-mode sequence parallelism (seq composed "
                    "with dp/tp) needs jax.shard_map with axis_names "
                    "support (jax >= 0.9); use a data x seq mesh on "
                    "this jax version") from None
            qs = _P(None, seq_axis)
            causal = self.causal
            if mask is not None:
                def island(qc, kc, vc, mc):
                    o = ring_self_attention(qc, kc, vc,
                                            axis_name=seq_axis,
                                            causal=causal, kv_mask=mc)
                    return o * mc[:, :, None, None]

                out = _shard_map(
                    island, mesh=seq_mesh,
                    in_specs=(qs, qs, qs, qs), out_specs=qs,
                    axis_names=frozenset({seq_axis}))(q, k, v, mask)
            else:
                def island(qc, kc, vc):
                    return ring_self_attention(qc, kc, vc,
                                               axis_name=seq_axis,
                                               causal=causal)

                out = _shard_map(
                    island, mesh=seq_mesh,
                    in_specs=(qs, qs, qs), out_specs=qs,
                    axis_names=frozenset({seq_axis}))(q, k, v)
        elif seq_axis is not None:
            # manual sequence-parallel step: x is the LOCAL (B, T/n, C)
            # chunk of a sequence sharded over `seq_axis`; attention
            # must span the whole distributed sequence, so ride the
            # ring (exact, differentiable, kernels on TPU). A
            # key-padding mask chunk rotates with its K/V block; padded
            # query rows are zeroed here (Layer.java:317 contract).
            from deeplearning4j_tpu.parallel.ring_attention import (
                ring_self_attention)
            out = ring_self_attention(q, k, v, axis_name=seq_axis,
                                      causal=self.causal,
                                      kv_mask=mask)
            if mask is not None:
                out = out * mask[:, :, None, None]
        elif mask is not None:
            # padded keys must leave the softmax DENOMINATOR, not just
            # contribute zero values — zeroing k/v would still give each
            # masked position weight exp(0) and dilute every real token.
            # The kv_mask-aware kernels handle this exactly, so
            # variable-length batches KEEP the flash kernel; padded
            # query rows are zeroed here (Layer.java:317 contract).
            out = flash_attention(q, k, v, causal=self.causal,
                                  kv_mask=mask)
            out = out * mask[:, :, None, None]
        else:
            out = flash_attention(q, k, v, causal=self.causal)
        out = out.reshape(B, T, self.n_out)
        proj = out @ params["Wo"]
        if self.out_bias:
            proj = proj + params["bo"]
        return proj, state

    def _project_qkv(self, params, x):
        """The shared q/k/v projection (+optional biases) and head
        split — ONE implementation for apply and apply_stream, so
        full-sequence and streaming outputs cannot drift."""
        B, T, _ = x.shape
        H = self.n_heads
        Dh = self.n_out // H
        q = x @ params["Wq"]
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        if self.qkv_bias:
            q = q + params["bq"]
            k = k + params["bk"]
            v = v + params["bv"]
        split = lambda y: y.reshape(B, T, H, Dh)
        return split(q), split(k), split(v)

    # ---- stateful streaming inference (rnnTimeStep contract,
    #      MultiLayerNetwork.java:2656): the attention analog of a
    #      recurrent carry is the KV CACHE ----
    def apply_stream(self, params, cache, x):
        """Incremental decode: ``x`` is the NEW (B, t, C) chunk;
        ``cache`` holds the k/v history (None at sequence start).
        Returns (out, new_cache); feeding chunks sequentially equals
        one full-sequence causal forward (tested). Eager-mode path
        (rnn_time_step is not jitted), so the cache grows by concat —
        no static max length needed. Requires causal=True: streaming
        non-causal attention would need future tokens."""
        if not self.causal:
            raise ValueError(
                "apply_stream requires causal=True: non-causal "
                "attention needs future timesteps — use output() on "
                "the full sequence instead")
        B, t, _ = x.shape
        q, k, v = self._project_qkv(params, x)
        if cache is None:
            n_cached = 0
            k_full, v_full = k, v
        else:
            n_cached = cache["k"].shape[1]
            k_full = jnp.concatenate([cache["k"], k], axis=1)
            v_full = jnp.concatenate([cache["v"], v], axis=1)
        out = _stream_attention(q, k_full, v_full, n_cached)
        out = out.reshape(B, t, self.n_out)
        proj = out @ params["Wo"]
        if self.out_bias:
            proj = proj + params["bo"]
        return proj, {"k": k_full, "v": v_full}

    # ---- jitted bounded-cache streaming (round-4 verdict weak #7:
    #      the eager concat cache is O(T^2) copy traffic with a
    #      dispatch per token; this variant carries a FIXED-capacity
    #      cache with static shapes so the whole token step jits) ----
    def zero_stream_cache(self, batch: int, capacity: int, dtype):
        H = self.n_heads
        Dh = self.n_out // H
        # two DISTINCT buffers: the session donates the cache to the
        # jitted step, and donating one aliased array twice is a
        # runtime error
        return {"k": jnp.zeros((batch, capacity, H, Dh), dtype),
                "v": jnp.zeros((batch, capacity, H, Dh), dtype)}

    def apply_stream_bounded(self, params, cache, x, pos):
        """One jittable decode step: ``x`` is the new (B, t, C) chunk,
        ``cache`` a fixed-capacity {'k','v'} of shape (B, CAP, H, Dh),
        ``pos`` the traced count of valid cached tokens. Writes the
        chunk at [pos, pos+t) in place (dynamic_update_slice — O(t)
        traffic, vs the eager path's O(pos) concat) and attends the
        new queries over the full capacity with a single positional
        mask: query i (global pos+i) sees keys k_pos <= pos+i, which
        simultaneously hides unwritten tail slots, stale slots past
        pos+t, and in-chunk future tokens. Returns (out, cache) —
        the caller advances pos. Capacity bounds are the CALLER's to
        enforce (they are static host decisions; see
        models/streaming.py)."""
        if not self.causal:
            raise ValueError(
                "apply_stream_bounded requires causal=True: streaming "
                "non-causal attention would need future timesteps")
        B, t, _ = x.shape
        q, k, v = self._project_qkv(params, x)
        zero = jnp.zeros((), jnp.int32)
        k_cache = jax.lax.dynamic_update_slice(
            cache["k"], k.astype(cache["k"].dtype),
            (zero, pos, zero, zero))
        v_cache = jax.lax.dynamic_update_slice(
            cache["v"], v.astype(cache["v"].dtype),
            (zero, pos, zero, zero))
        cap = k_cache.shape[1]
        scale = q.shape[-1] ** -0.5
        from deeplearning4j_tpu.ops.attention import _NEG_INF
        logits = jnp.einsum("bqhd,bkhd->bhqk", q,
                            k_cache.astype(q.dtype)) * scale
        k_pos = jnp.arange(cap)[None, :]
        q_pos = pos + jnp.arange(t)[:, None]
        logits = jnp.where((k_pos <= q_pos)[None, None], logits,
                           _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         v_cache.astype(q.dtype))
        out = out.reshape(B, t, self.n_out)
        proj = out @ params["Wo"]
        if self.out_bias:
            proj = proj + params["bo"]
        return proj, {"k": k_cache, "v": v_cache}

    # ---- paged (block) KV cache: the vLLM memory model over the
    #      same math as apply_stream_bounded. The session owns ONE
    #      physical pool of fixed-size pages per layer; each slot sees
    #      a VIRTUAL contiguous cache assembled by gathering its page
    #      table — so KV memory is bounded by the pool, not by
    #      slots x max-capacity (models/paged_kv.py) ----
    def zero_page_pool(self, n_pages: int, page_size: int, dtype):
        """Physical page pool for this layer: ``zero_stream_cache``
        with (batch, capacity) = (n_pages, page_size) — a page IS a
        page_size-token cache row."""
        return self.zero_stream_cache(n_pages, page_size, dtype)

    def apply_stream_paged(self, params, pool, table, pos, x):
        """One jittable decode step over paged caches for ALL slots at
        once. ``x`` is the new (S, t, C) chunk (one row per slot),
        ``pool`` the physical {'k','v'} pages of shape
        (n_pages, page_size, H, Dh), ``table`` the (S, P) per-slot
        page table, ``pos`` the (S,) per-slot token positions. Writes
        each slot's new k/v at its (page, offset) — scatter indices
        are unique because written pages are slot-exclusive (shared
        prefix pages are read-only; divergence is copy-on-write at
        admission, host-side) — then attends each slot's queries over
        its GATHERED virtual cache of P*page_size positions with the
        same k_pos <= q_pos mask as the dense step. With
        P*page_size == dense capacity the math is position-for-
        position identical to apply_stream_bounded (greedy-token
        parity is tested). Returns (out, pool)."""
        if not self.causal:
            raise ValueError(
                "apply_stream_paged requires causal=True: streaming "
                "non-causal attention would need future timesteps")
        S, t, _ = x.shape
        ps = pool["k"].shape[1]
        q, k, v = self._project_qkv(params, x)
        # write positions for the t new tokens of every slot
        wpos = pos[:, None] + jnp.arange(t)[None, :]        # (S, t)
        page_ids = jnp.take_along_axis(table, wpos // ps, axis=1)
        offs = wpos % ps
        k_pool = pool["k"].at[page_ids, offs].set(
            k.astype(pool["k"].dtype))
        v_pool = pool["v"].at[page_ids, offs].set(
            v.astype(pool["v"].dtype))
        # gather each slot's virtual cache: (S, P, ps, H, Dh) ->
        # (S, P*ps, H, Dh). Stale/unassigned table entries gather
        # garbage pages, but their virtual positions exceed pos and
        # the mask zeroes them exactly (exp(_NEG_INF - max) == 0.0)
        P = table.shape[1]
        H = self.n_heads
        Dh = self.n_out // H
        k_cache = k_pool[table].reshape(S, P * ps, H, Dh)
        v_cache = v_pool[table].reshape(S, P * ps, H, Dh)
        scale = q.shape[-1] ** -0.5
        from deeplearning4j_tpu.ops.attention import _NEG_INF
        logits = jnp.einsum("bqhd,bkhd->bhqk", q,
                            k_cache.astype(q.dtype)) * scale
        k_pos = jnp.arange(P * ps)[None, None, :]           # (1,1,K)
        q_pos = wpos[:, :, None]                            # (S,t,1)
        logits = jnp.where((k_pos <= q_pos)[:, None], logits,
                           _NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         v_cache.astype(q.dtype))
        out = out.reshape(S, t, self.n_out)
        proj = out @ params["Wo"]
        if self.out_bias:
            proj = proj + params["bo"]
        return proj, {"k": k_pool, "v": v_pool}


@register_layer
@dataclasses.dataclass
class TransformerEncoderLayer(BaseLayer):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x))."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    n_heads: int = 4
    ffn_multiplier: int = 4
    causal: bool = False
    activation: str = "gelu"

    # LN + residual + per-token MLP are pointwise in time; the inner
    # attention routes itself through the ring (seq_context)
    seq_parallelizable = True

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.size
        if self.n_out is None:
            self.n_out = self.n_in

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        if self.n_in != self.n_out:
            raise ValueError("TransformerEncoderLayer requires "
                             "n_in == n_out (residual)")
        ka, k1, k2 = jax.random.split(key, 3)
        pd = dtypes.policy().param_dtype
        d = self.n_out
        dff = d * self.ffn_multiplier
        attn_p, _ = self._ensure_attn().initialize(
            ka, InputType.recurrent(d))
        p = {
            "attn": attn_p,
            "ln1_g": jnp.ones((d,), pd), "ln1_b": jnp.zeros((d,), pd),
            "ln2_g": jnp.ones((d,), pd), "ln2_b": jnp.zeros((d,), pd),
            "W1": self._sample_w(k1, (d, dff), d, dff),
            "b1": jnp.zeros((dff,), pd),
            "W2": self._sample_w(k2, (dff, d), dff, d),
            "b2": jnp.zeros((d,), pd),
        }
        return p, {}

    def _ensure_attn(self):
        if not hasattr(self, "_attn"):
            self._attn = SelfAttentionLayer(
                n_in=self.n_in, n_out=self.n_out, n_heads=self.n_heads,
                causal=self.causal, weight_init=self.weight_init)
        return self._attn

    def apply(self, params, state, x, *, training=False, rng=None,
              mask=None):
        self._ensure_attn()
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        a, _ = self._attn.apply(params["attn"], {}, h,
                                training=training, rng=rng, mask=mask)
        x = x + a
        return x + self._mlp_half(params, x), state

    def _mlp_half(self, params, x):
        """Pre-LN MLP residual branch — shared by apply and
        apply_stream (per-token, so streaming needs no carry)."""
        h = _layer_norm(x, params["ln2_g"], params["ln2_b"])
        act = self.activation_fn()
        return act(h @ params["W1"] + params["b1"]) @ params["W2"] \
            + params["b2"]

    def apply_stream(self, params, cache, x):
        """Incremental decode through the full pre-LN block: the
        inner attention carries the KV cache, the LN/MLP halves are
        per-token (see SelfAttentionLayer.apply_stream)."""
        self._ensure_attn()
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        a, cache = self._attn.apply_stream(params["attn"], cache, h)
        x = x + a
        return x + self._mlp_half(params, x), cache

    def zero_stream_cache(self, batch: int, capacity: int, dtype):
        return self._ensure_attn().zero_stream_cache(batch, capacity,
                                                     dtype)

    def apply_stream_bounded(self, params, cache, x, pos):
        """Jittable bounded-cache decode step through the pre-LN
        block (see SelfAttentionLayer.apply_stream_bounded)."""
        self._ensure_attn()
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        a, cache = self._attn.apply_stream_bounded(params["attn"],
                                                   cache, h, pos)
        x = x + a
        return x + self._mlp_half(params, x), cache

    def zero_page_pool(self, n_pages: int, page_size: int, dtype):
        return self._ensure_attn().zero_page_pool(n_pages, page_size,
                                                  dtype)

    def apply_stream_paged(self, params, pool, table, pos, x):
        """Paged-cache decode step through the pre-LN block (see
        SelfAttentionLayer.apply_stream_paged)."""
        self._ensure_attn()
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        a, pool = self._attn.apply_stream_paged(params["attn"], pool,
                                                table, pos, h)
        x = x + a
        return x + self._mlp_half(params, x), pool


def _stream_attention(q, k_full, v_full, n_cached: int):
    """Exact attention of the NEW chunk's queries over the full
    cached+new history, causal within the chunk: new position i
    (global n_cached + i) sees keys [0, n_cached + i]."""
    from deeplearning4j_tpu.ops.attention import _NEG_INF
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_full) * scale
    t_new = q.shape[1]
    k_pos = jnp.arange(k_full.shape[1])[None, :]
    q_pos = n_cached + jnp.arange(t_new)[:, None]
    logits = jnp.where((k_pos <= q_pos)[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_full)
