"""Recurrent layers.

Replaces the reference's hand-written LSTM math
(nn/layers/recurrent/LSTMHelpers.java:69 activateHelper, :400
backpropGradientHelper — 793 LoC of manual forward/backward) and the
cuDNN RNN binding (CudnnLSTMHelper.java) with a single ``lax.scan``
forward; the backward pass is ``jax.grad`` through the scan. The
per-timestep cell is one fused (B, n_in+n_out) x (n_in+n_out, 4*n_out)
matmul — MXU-shaped.

Gate packing order on the 4*n_out axis: [input, forget, output, cell(g)].

Stateful streaming inference (reference ``rnnTimeStep``,
MultiLayerNetwork.java:2656) is supported via ``apply_rnn`` which takes
and returns the carried (h, c); executors keep a per-layer state map.

Masking (reference: Layer.feedForwardMaskArray, MaskedReductionUtil):
at masked timesteps the carried state does not advance and the output
is zeroed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayer, Layer, register_layer,
)
from deeplearning4j_tpu.nn.conf.layers.base import layer_from_dict
from deeplearning4j_tpu.nn.conf.layers.output import LossLayer
from deeplearning4j_tpu.nn import activations

__all__ = ["LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "Bidirectional",
           "SimpleRnn", "LastTimeStep", "RnnLossLayer"]


@dataclasses.dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    activation: str = "tanh"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.size

    def zero_state(self, batch: int, dtype=None):
        from deeplearning4j_tpu import dtypes as dtypes_mod
        dt = dtype or dtypes_mod.policy().param_dtype
        # distinct h/c buffers: streaming sessions donate the carry
        # to the jitted step, and donating one aliased array twice
        # is a runtime error
        return (jnp.zeros((batch, self.n_out), dt),
                jnp.zeros((batch, self.n_out), dt))

    def apply_rnn(self, params, x, carry, *, training=False, rng=None,
                  mask=None):
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        out, _ = self.apply_rnn(params, x,
                                self.zero_state(x.shape[0], x.dtype),
                                training=training, rng=rng, mask=mask)
        return out, state


@register_layer
@dataclasses.dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM, no peepholes (nn/conf/layers/LSTM.java).

    ``forget_gate_bias_init`` mirrors the reference's
    forgetGateBiasInit (default 1.0, GravesLSTM.java builder).
    """

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        k1, k2 = jax.random.split(key)
        pd = dtypes.policy().param_dtype
        n, m = self.n_in, self.n_out
        b = jnp.zeros((4 * m,), pd)
        # forget-gate block is [m:2m] in the packed order [i,f,o,g]
        b = b.at[m:2 * m].set(self.forget_gate_bias_init)
        return {
            "Wx": self._sample_w(k1, (n, 4 * m), n + m, m),
            "Wh": self._sample_w(k2, (m, 4 * m), n + m, m),
            "b": b,
        }, {}

    def _gates(self, params, xt, h):
        return xt @ params["Wx"] + h @ params["Wh"] + params["b"]

    def _cell(self, params, xt, h, c):
        m = self.n_out
        z = self._gates(params, xt, h)
        gate = activations.get(self.gate_activation)
        act = self.activation_fn()
        i = gate(z[:, 0 * m:1 * m])
        f = gate(z[:, 1 * m:2 * m])
        o = gate(z[:, 2 * m:3 * m])
        g = act(z[:, 3 * m:4 * m])
        c_new = f * c + i * g
        h_new = o * act(c_new)
        return h_new, c_new

    def apply_rnn(self, params, x, carry, *, training=False, rng=None,
                  mask=None):
        h0, c0 = carry

        def step(carry, inp):
            h, c = carry
            if mask is not None:
                xt, mt = inp
            else:
                xt = inp
            h_new, c_new = self._cell(params, xt, h, c)
            if mask is not None:
                mt = mt[:, None]
                h_new = jnp.where(mt > 0, h_new, h)
                c_new = jnp.where(mt > 0, c_new, c)
                out = h_new * mt
            else:
                out = h_new
            return (h_new, c_new), out

        xs = jnp.swapaxes(x, 0, 1)                    # (T,B,C)
        inputs = (xs, jnp.swapaxes(mask, 0, 1)) if mask is not None else xs
        (h, c), ys = lax.scan(step, (h0, c0), inputs)
        return jnp.swapaxes(ys, 0, 1), (h, c)


@register_layer
@dataclasses.dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (nn/conf/layers/GravesLSTM.java,
    math in LSTMHelpers.java — peepholes w_ci, w_cf on pre-state, w_co
    on post-state, per Graves 2013)."""

    def initialize(self, key, input_type: InputType):
        params, state = super().initialize(key, input_type)
        pd = dtypes.policy().param_dtype
        m = self.n_out
        params["wc"] = jnp.zeros((3 * m,), pd)   # [ci, cf, co]
        return params, state

    def _cell(self, params, xt, h, c):
        m = self.n_out
        z = self._gates(params, xt, h)
        gate = activations.get(self.gate_activation)
        act = self.activation_fn()
        wci = params["wc"][0 * m:1 * m]
        wcf = params["wc"][1 * m:2 * m]
        wco = params["wc"][2 * m:3 * m]
        i = gate(z[:, 0 * m:1 * m] + c * wci)
        f = gate(z[:, 1 * m:2 * m] + c * wcf)
        g = act(z[:, 3 * m:4 * m])
        c_new = f * c + i * g
        o = gate(z[:, 2 * m:3 * m] + c_new * wco)
        h_new = o * act(c_new)
        return h_new, c_new


@register_layer
@dataclasses.dataclass
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla RNN: h_t = act(x_t Wx + h_{t-1} Wh + b)."""

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        k1, k2 = jax.random.split(key)
        pd = dtypes.policy().param_dtype
        return {
            "Wx": self._sample_w(k1, (self.n_in, self.n_out),
                                 self.n_in, self.n_out),
            "Wh": self._sample_w(k2, (self.n_out, self.n_out),
                                 self.n_out, self.n_out),
            "b": jnp.full((self.n_out,), self.bias_init, pd),
        }, {}

    def apply_rnn(self, params, x, carry, *, training=False, rng=None,
                  mask=None):
        h0, _ = carry
        act = self.activation_fn()

        def step(h, inp):
            if mask is not None:
                xt, mt = inp
            else:
                xt = inp
            h_new = act(xt @ params["Wx"] + h @ params["Wh"] + params["b"])
            if mask is not None:
                mt = mt[:, None]
                h_new = jnp.where(mt > 0, h_new, h)
                out = h_new * mt
            else:
                out = h_new
            return h_new, out

        xs = jnp.swapaxes(x, 0, 1)
        inputs = (xs, jnp.swapaxes(mask, 0, 1)) if mask is not None else xs
        h, ys = lax.scan(step, h0, inputs)
        return jnp.swapaxes(ys, 0, 1), (h, h)


@register_layer
@dataclasses.dataclass
class Bidirectional(Layer):
    """Bidirectional wrapper (reference nn/conf/layers/recurrent/
    Bidirectional.java semantics): runs the wrapped recurrent layer
    forward and (on a time-reversed copy) backward, merging by
    mode ∈ {concat, add, mul, ave}."""

    fwd: Optional[dict] = None          # serialized wrapped-layer config
    mode: str = "concat"

    def __post_init__(self):
        if isinstance(self.fwd, Layer):
            self._fwd_layer = self.fwd
            self.fwd = self.fwd.to_dict()
        elif self.fwd is not None:
            self._fwd_layer = layer_from_dict(self.fwd)
        else:
            self._fwd_layer = None

    @property
    def wrapped(self) -> BaseRecurrentLayer:
        return self._fwd_layer

    def set_n_in(self, input_type: InputType) -> None:
        self.wrapped.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        base = self.wrapped.output_type(input_type)
        n = base.size * 2 if self.mode == "concat" else base.size
        return InputType.recurrent(n, base.timesteps)

    def initialize(self, key, input_type: InputType):
        kf, kb = jax.random.split(key)
        self.wrapped.set_n_in(input_type)
        pf, _ = self.wrapped.initialize(kf, input_type)
        pb, _ = self.wrapped.initialize(kb, input_type)
        self.fwd = self.wrapped.to_dict()   # capture inferred n_in
        return {"fwd": pf, "bwd": pb}, {}

    def _reverse(self, x, mask):
        if mask is None:
            return jnp.flip(x, axis=1)
        # flip only the valid prefix per example (DL4J reverses w.r.t.
        # actual sequence length under masking)
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)   # (B,)
        T = x.shape[1]
        idx = jnp.arange(T)[None, :]                         # (1,T)
        rev = lengths[:, None] - 1 - idx
        rev = jnp.where(rev >= 0, rev, idx)
        return jnp.take_along_axis(x, rev[..., None], axis=1)

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        lay = self.wrapped
        z = lay.zero_state(x.shape[0])
        out_f, _ = lay.apply_rnn(params["fwd"], x, z, training=training,
                                 rng=rng, mask=mask)
        xr = self._reverse(x, mask)
        out_b, _ = lay.apply_rnn(params["bwd"], xr, z, training=training,
                                 rng=rng, mask=mask)
        out_b = self._reverse(out_b, mask)
        if self.mode == "concat":
            y = jnp.concatenate([out_f, out_b], axis=-1)
        elif self.mode == "add":
            y = out_f + out_b
        elif self.mode == "mul":
            y = out_f * out_b
        elif self.mode == "ave":
            y = 0.5 * (out_f + out_b)
        else:
            raise ValueError(self.mode)
        return y, state

    def to_dict(self) -> dict:
        return {"@type": "Bidirectional", "name": self.name,
                "dropout": self.dropout, "fwd": self.fwd, "mode": self.mode}


@register_layer
@dataclasses.dataclass
class GravesBidirectionalLSTM(Bidirectional):
    """(nn/conf/layers/GravesBidirectionalLSTM.java) — a bidirectional
    GravesLSTM with concat merge, kept as its own type for config parity."""

    n_in: Optional[int] = None
    n_out: Optional[int] = None
    activation: str = "tanh"
    weight_init: str = "xavier"
    forget_gate_bias_init: float = 1.0

    def __post_init__(self):
        if self.fwd is None and self.n_out is not None:
            self._fwd_layer = GravesLSTM(
                n_in=self.n_in, n_out=self.n_out, activation=self.activation,
                weight_init=self.weight_init,
                forget_gate_bias_init=self.forget_gate_bias_init)
            self.fwd = self._fwd_layer.to_dict()
        else:
            super().__post_init__()


@register_layer
@dataclasses.dataclass
class LastTimeStep(Layer):
    """Wrapper extracting the last (unmasked) timestep → FF output
    (reference nn/conf/layers/recurrent/LastTimeStep.java +
    LastTimeStepVertex)."""

    underlying: Optional[dict] = None

    def __post_init__(self):
        if isinstance(self.underlying, Layer):
            self._under = self.underlying
            self.underlying = self._under.to_dict()
        elif self.underlying is not None:
            self._under = layer_from_dict(self.underlying)
        else:
            self._under = None

    def set_n_in(self, input_type: InputType) -> None:
        self._under.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        base = self._under.output_type(input_type)
        return InputType.feed_forward(base.size)

    def initialize(self, key, input_type: InputType):
        p, s = self._under.initialize(key, input_type)
        self.underlying = self._under.to_dict()
        return p, s

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        y, new_state = self._under.apply(params, state, x, training=training,
                                         rng=rng, mask=mask)
        if mask is None:
            return y[:, -1, :], new_state
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            y, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0, :], \
            new_state


@register_layer
@dataclasses.dataclass
class RnnLossLayer(LossLayer):
    """Time-distributed loss layer without weights
    (nn/conf/layers/RnnLossLayer semantics).

    NOT seq_parallelizable: the inherited loss SUMS over timesteps per
    example (DL4J score convention) instead of averaging, so the seq
    step's mean-of-local-means normalization would shrink gradients by
    the seq-axis factor. Use RnnOutputLayer (which normalizes by T)
    for sequence-parallel training."""

    def output_type(self, input_type: InputType) -> InputType:
        return input_type
