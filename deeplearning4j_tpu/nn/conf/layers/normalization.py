"""Normalization layers.

BatchNormalization replaces both the reference's Java impl
(nn/layers/normalization/BatchNormalization.java) and its cuDNN helper
(CudnnBatchNormalizationHelper.java). Running mean/var live in the
layer *state* pytree and are updated functionally at train time — the
executor threads state through the jitted train step (no mutation, no
workspaces).

LocalResponseNormalization mirrors
nn/layers/normalization/LocalResponseNormalization.java /
CudnnLocalResponseNormalizationHelper.java (AlexNet-era LRN).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    BaseLayer, Layer, register_layer,
)

__all__ = ["BatchNormalization", "LayerNormalization",
           "LocalResponseNormalization"]


@register_layer
@dataclasses.dataclass
class BatchNormalization(BaseLayer):
    """(nn/conf/layers/BatchNormalization.java). Normalizes over batch
    (+spatial for CNN input); gamma/beta trainable unless ``lock_gamma_beta``.
    ``decay`` matches the reference's running-average decay (default 0.9)."""

    n_out: Optional[int] = None      # inferred from input type
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma: float = 1.0
    beta: float = 0.0

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_out is None:
            if input_type.kind == "cnn":
                self.n_out = input_type.channels
            else:
                self.n_out = input_type.flat_size()

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        pd = dtypes.policy().param_dtype
        n = self.n_out
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((n,), self.gamma, pd),
                      "beta": jnp.full((n,), self.beta, pd)}
        state = {"mean": jnp.zeros((n,), jnp.float32),
                 "var": jnp.ones((n,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))   # all but channel/feature axis
        if training:
            # single-pass statistics: var = E[x²] − E[x]² lets XLA fuse
            # both reductions into one sweep. ALWAYS in float32 — in
            # bf16 the subtraction catastrophically cancels whenever
            # |mean|/std ≳ 16 (flax BatchNorm makes the same choice)
            xs = jnp.asarray(x, jnp.float32)
            mean = jnp.mean(xs, axis=axes)
            mean_sq = jnp.mean(jnp.square(xs), axis=axes)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv
        if not self.lock_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        else:
            y = y * self.gamma + self.beta
        return self.activation_fn()(y), new_state


@register_layer
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """Across-channel LRN (nn/conf/layers/LocalResponseNormalization.java):
    y = x / (k + alpha * sum_{j in window} x_j^2)^beta."""

    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75
    n: int = 5

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        # channel-last; windowed sum of squares over channel axis
        sq = x * x
        half = self.n // 2
        window = (1,) * (x.ndim - 1) + (self.n,)
        strides = (1,) * x.ndim
        pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, pad)
        return x / (self.k + self.alpha * ssum) ** self.beta, state


def layer_norm(x, gamma, beta, eps=1e-5):
    """Canonical last-axis layer norm — shared by the standalone
    LayerNormalization layer and TransformerEncoderLayer's inlined
    pre-LN blocks (one implementation, no drift)."""
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * gamma + beta


@register_layer
@dataclasses.dataclass
class LayerNormalization(Layer):
    """Per-example feature normalization (Ba et al. 2016): normalize
    over the LAST axis with learned gamma/beta. Stateless (unlike
    BatchNormalization — no running stats), so it composes with every
    parallelism mode including sequence sharding (pointwise in time)
    and the device-resident pipeline. The reference predates LN; this
    is a capability extension matching the Keras/transformer-era
    surface (TransformerEncoderLayer inlines the same math)."""

    n_in: Optional[int] = None
    eps: float = 1e-5

    seq_parallelizable = True          # per-token normalization

    def set_n_in(self, input_type: InputType) -> None:
        if self.n_in is None:
            self.n_in = input_type.size

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        pd = dtypes.policy().param_dtype
        return {"gamma": jnp.ones((self.n_in,), pd),
                "beta": jnp.zeros((self.n_in,), pd)}, {}

    def apply(self, params, state, x, *, training=False, rng=None,
              mask=None):
        return layer_norm(x, params["gamma"], params["beta"],
                          self.eps), state
