"""Output layers: loss-bearing heads.

Reference: nn/conf/layers/OutputLayer.java / RnnOutputLayer.java /
LossLayer.java / CenterLossOutputLayer.java; impls under
nn/layers/BaseOutputLayer.java, nn/layers/training/.

Each output layer is a Dense-like transform + activation, plus a
``loss_fn(labels, activations, mask)`` hook used by the executors to
assemble the total training loss (score). Stable fused
softmax/sigmoid+CE paths are used when activation/loss pairs match.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes
from deeplearning4j_tpu.nn import losses as losses_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.base import (
    FeedForwardLayer, BaseLayer, register_layer,
)

__all__ = ["OutputLayer", "RnnOutputLayer", "LossLayer",
           "CenterLossOutputLayer"]


def _stable_ce(logits, labels, mask, kind):
    """Fused log-softmax / log-sigmoid cross-entropy (per-example).
    Half-precision logits are promoted to f32: under the bf16 policy
    the hidden activations are bfloat16 (MXU/HBM-native) but exp/log
    at the loss must not be. promote_half never DOWNcasts — the f64
    gradient checker must stay f64."""
    logits = dtypes.promote_half(logits)
    labels = dtypes.promote_half(labels)
    if kind == "softmax":
        logp = jax.nn.log_softmax(logits, axis=-1)
        per = -labels * logp
    else:  # sigmoid + binary xent
        per = (jnp.maximum(logits, 0) - logits * labels
               + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    if mask is not None:
        per = per * mask
    return jnp.sum(per, axis=tuple(range(1, per.ndim)))


@register_layer
@dataclasses.dataclass
class OutputLayer(FeedForwardLayer):
    """Dense + activation + loss (nn/conf/layers/OutputLayer.java)."""

    loss: str = "mcxent"
    activation: str = "softmax"

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        p = {"W": self._sample_w(key, (self.n_in, self.n_out),
                                 self.n_in, self.n_out)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init,
                              dtypes.policy().param_dtype)
        return p, {}

    def _pre_output(self, params, x, *, training, rng):
        x = self.apply_input_dropout(x, training=training, rng=rng)
        if x.ndim > 2 and not isinstance(self, RnnOutputLayer):
            x = x.reshape(x.shape[0], -1)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z

    def apply(self, params, state, x, *, training=False, rng=None, mask=None):
        # predictions/softmax never in half precision — under the
        # bf16 policy only HIDDEN activations ride bfloat16
        z = dtypes.promote_half(
            self._pre_output(params, x, training=training, rng=rng))
        return self.activation_fn()(z), state

    def has_loss(self) -> bool:
        return True

    def _fused_kind(self):
        a, l = self.activation.lower(), self.loss.lower()
        if a == "softmax" and l in ("mcxent", "negativeloglikelihood"):
            return "softmax"
        if a == "sigmoid" and l == "xent":
            return "sigmoid"
        return None

    def loss_from_input(self, params, x, labels, *, training, rng, mask=None):
        """Mean per-example score given the layer *input* (pre-dense)."""
        z = self._pre_output(params, x, training=training, rng=rng)
        kind = self._fused_kind()
        if kind is not None:
            per_ex = _stable_ce(z, labels, mask, kind)
        else:
            preds = self.activation_fn()(dtypes.promote_half(z))
            per_ex = losses_mod.get(self.loss)(labels, preds, mask)
        return jnp.mean(per_ex)


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(OutputLayer):
    """Time-distributed output layer (nn/conf/layers/RnnOutputLayer.java).
    Input (B,T,F) → (B,T,n_out); loss masked per timestep. DL4J reshapes
    to 2-d ((B*T),F) internally (FeedForwardToRnnPreProcessor) — here the
    matmul is applied directly on the 3-d array."""

    # per-timestep logits; local-chunk mean loss pmeans to the global
    # mean under uniform shards (the wrapper enforces divisibility)
    seq_parallelizable = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.n_out, input_type.timesteps)

    def loss_from_input(self, params, x, labels, *, training, rng, mask=None):
        z = self._pre_output(params, x, training=training, rng=rng)
        # mask: (B,T) → broadcast over features
        m = mask[..., None] if (mask is not None and mask.ndim == 2) else mask
        kind = self._fused_kind()
        if kind is not None:
            per = _stable_ce(z, labels, m, kind)      # (B,) summed over T,F
        else:
            preds = self.activation_fn()(dtypes.promote_half(z))
            per = losses_mod.get(self.loss)(labels, preds, m)
        if mask is not None:
            from deeplearning4j_tpu.parallel.seq_context import (
                current_loss_axes)
            axes = current_loss_axes()
            if axes:
                # sequence-parallel trace: the masked mean's
                # denominator is GLOBAL (shards hold different
                # unmasked-step counts). Scale by the shard count so
                # the wrapper's mean-of-local-losses equals
                # Σ per / Σ mask over the whole batch.
                import jax
                total = jax.lax.psum(jnp.sum(mask), axes)
                n_sh = 1
                for a in axes:
                    n_sh *= jax.lax.psum(1, a)
                return jnp.sum(per) * n_sh / jnp.maximum(total, 1.0)
            # DL4J averages over *present* timesteps across the batch
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            return jnp.sum(per) / denom
        return jnp.mean(per) / z.shape[1]


@register_layer
@dataclasses.dataclass
class LossLayer(OutputLayer):
    """Loss without weights (nn/conf/layers/LossLayer.java): input passes
    through activation straight to the loss."""

    def set_n_in(self, input_type: InputType) -> None:
        # weightless: n_out is the input width, never user-required
        # (the base class refuses a missing n_out)
        if self.n_in is None:
            self.n_in = input_type.flat_size()
        if self.n_out is None:
            self.n_out = self.n_in

    def initialize(self, key, input_type: InputType):
        self.set_n_in(input_type)
        self.n_out = self.n_in
        return {}, {}

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def _pre_output(self, params, x, *, training, rng):
        return self.apply_input_dropout(x, training=training, rng=rng)


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (nn/conf/layers/CenterLossOutputLayer.java,
    impl nn/layers/training/CenterLossOutputLayer.java). Per-class
    feature centers live in *state* and are EMA-updated at train time
    (alpha), with the center-loss term weighted by lambda."""

    alpha: float = 0.05
    lambda_: float = 2e-4

    def initialize(self, key, input_type: InputType):
        params, _ = super().initialize(key, input_type)
        centers = jnp.zeros((self.n_out, self.n_in),
                            dtypes.policy().param_dtype)
        return params, {"centers": centers}

    def center_loss(self, state, x, labels):
        # x: (B, n_in) features; labels one-hot (B, n_out): squared
        # distances must not inherit bf16 activation precision
        x = dtypes.promote_half(x)
        labels = dtypes.promote_half(labels)
        assigned = labels @ state["centers"]           # (B, n_in)
        return 0.5 * jnp.mean(jnp.sum((x - assigned) ** 2, axis=-1))

    def update_centers(self, state, x, labels):
        counts = jnp.sum(labels, axis=0)[:, None]       # (n_out,1)
        sums = labels.T @ x                             # (n_out, n_in)
        mean_per_class = sums / jnp.maximum(counts, 1.0)
        has = (counts > 0)
        new = jnp.where(
            has, (1 - self.alpha) * state["centers"]
            + self.alpha * mean_per_class, state["centers"])
        return {**state, "centers": new}

    def loss_from_input(self, params, x, labels, *, training, rng, mask=None):
        base = super().loss_from_input(params, x, labels, training=training,
                                       rng=rng, mask=mask)
        return base  # center term added by the executor (needs state)
