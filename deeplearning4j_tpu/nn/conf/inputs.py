"""InputType: symbolic activation shapes for config-time inference.

Mirrors the reference's ``nn/conf/inputs/InputType.java`` +
``InputTypeUtil.java``: each layer config maps an input type to an
output type, letting the network builder infer nIn/nOut, validate
shapes, and auto-insert preprocessors between layer families
(CNN⇄FF⇄RNN) the way ``MultiLayerConfiguration.Builder`` does.

Unlike the reference (NCHW, channels-first, after DL4J's CNN format),
convolutional activations are **NHWC** — the TPU-native layout that XLA
tiles best. ``CNNFlat`` mirrors ``InputType.convolutionalFlat`` for
flattened image rows (e.g. MNIST 784).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

__all__ = ["InputType"]


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str                       # 'ff' | 'rnn' | 'cnn' | 'cnnflat' | 'cnn3d'
    size: Optional[int] = None      # ff/rnn feature size
    timesteps: Optional[int] = None          # rnn sequence length (may be None)
    height: Optional[int] = None
    width: Optional[int] = None
    channels: Optional[int] = None
    depth: Optional[int] = None     # cnn3d

    # ---- constructors (match InputType.feedForward/recurrent/... names) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", size=int(size),
                         timesteps=None if timesteps is None else int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=int(height), width=int(width),
                         channels=int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnnflat", height=int(height), width=int(width),
                         channels=int(channels))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int,
                        channels: int) -> "InputType":
        return InputType("cnn3d", depth=int(depth), height=int(height),
                         width=int(width), channels=int(channels))

    # ---- geometry ----
    def flat_size(self) -> int:
        if self.kind == "ff" or self.kind == "rnn":
            return self.size
        if self.kind in ("cnn", "cnnflat"):
            return self.height * self.width * self.channels
        if self.kind == "cnn3d":
            return self.depth * self.height * self.width * self.channels
        raise ValueError(self.kind)

    def array_shape(self, batch: int = -1) -> Tuple[int, ...]:
        """Concrete array shape (batch leading; NHWC for conv; NTC for rnn)."""
        if self.kind == "ff":
            return (batch, self.size)
        if self.kind == "rnn":
            return (batch, self.timesteps or -1, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnnflat":
            return (batch, self.height * self.width * self.channels)
        if self.kind == "cnn3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    # ---- serde ----
    def to_dict(self) -> dict:
        d = {"kind": self.kind}
        for f in ("size", "timesteps", "height", "width", "channels", "depth"):
            v = getattr(self, f)
            if v is not None:
                d[f] = v
        return d

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)

    def __repr__(self):
        if self.kind == "ff":
            return f"InputType.ff({self.size})"
        if self.kind == "rnn":
            return f"InputType.rnn({self.size}, t={self.timesteps})"
        if self.kind == "cnn":
            return f"InputType.cnn({self.height}x{self.width}x{self.channels})"
        if self.kind == "cnnflat":
            return (f"InputType.cnnflat({self.height}x{self.width}"
                    f"x{self.channels})")
        return f"InputType({self.to_dict()})"
