"""Graph vertex configs for ComputationGraph DAGs.

Mirrors nn/conf/graph/*.java (ElementWiseVertex, MergeVertex,
SubsetVertex, StackVertex, UnstackVertex, ScaleVertex, ShiftVertex,
L2NormalizeVertex, L2Vertex, PreprocessorVertex, ReshapeVertex,
PoolHelperVertex, rnn/LastTimeStepVertex, rnn/DuplicateToTimeSeriesVertex)
and their impls under nn/graph/vertex/impl/ (14 classes).

A vertex is a (possibly multi-input) pure function without trainable
params; layers are wrapped in :class:`LayerVertex` by the graph builder.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

__all__ = ["GraphVertex", "vertex_from_dict", "ElementWiseVertex",
           "MergeVertex", "SubsetVertex", "StackVertex", "UnstackVertex",
           "ScaleVertex", "ShiftVertex", "L2NormalizeVertex", "L2Vertex",
           "PreprocessorVertex", "ReshapeVertex", "PoolHelperVertex",
           "LastTimeStepVertex", "DuplicateToTimeSeriesVertex"]

_VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def combine_masks_or(masks):
    """Reference mask-combination rule (MergeVertex.java:229-252,
    ElementWiseVertex.java:146-160): if ANY input mask is absent the
    output mask is null (missing = "all steps present"); otherwise
    element-wise OR."""
    if not masks or any(m is None for m in masks):
        return None
    out = masks[0]
    for m in masks[1:]:
        out = jnp.maximum(out, m)
    return out


def vertex_from_dict(d: dict):
    d = dict(d)
    t = d.pop("@type")
    cls = _VERTEX_REGISTRY[t]
    return cls.from_dict(d)


@dataclasses.dataclass
class GraphVertex:
    # True iff apply() on (B, T, ...) inputs is exact when T is only a
    # LOCAL chunk of the sequence (pointwise in time) — gates the
    # wrapper's sequence-parallel step; see Layer.seq_parallelizable.
    # L2Normalize norms over TIME, Stack rides the batch axis,
    # LastTimeStep/DuplicateToTimeSeries/Reshape/Preprocessor reshape
    # time: those stay False.
    seq_parallelizable = False

    def apply(self, inputs, *, mask=None):
        raise NotImplementedError

    def propagate_mask(self, in_masks, inputs, mask_env=None):
        """Per-vertex mask routing (reference
        GraphVertex.feedForwardMaskArrays). ``in_masks`` aligns with
        ``inputs``; ``mask_env`` maps every already-computed vertex /
        network-input name to its mask (needed by vertices that
        reference a named input, e.g. DuplicateToTimeSeriesVertex)."""
        return combine_masks_or(in_masks)

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d

    @classmethod
    def from_dict(cls, d: dict):
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in d:
                v = d[f.name]
                kw[f.name] = tuple(v) if isinstance(v, list) else v
        return cls(**kw)


@register_vertex
@dataclasses.dataclass
class ElementWiseVertex(GraphVertex):
    """(nn/conf/graph/ElementWiseVertex.java:42-43). op ∈ {add,
    subtract, product, average, max}."""

    seq_parallelizable = True          # elementwise

    op: str = "add"

    def apply(self, inputs, *, mask=None):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract requires exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown ElementWise op '{self.op}'")


@register_vertex
@dataclasses.dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis
    (nn/conf/graph/MergeVertex.java — reference concatenates on dim 1 =
    channels under NCHW; channel-last here)."""

    seq_parallelizable = True          # feature-axis concat

    def apply(self, inputs, *, mask=None):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, *ts: InputType) -> InputType:
        t0 = ts[0]
        if t0.kind == "cnn":
            return InputType.convolutional(t0.height, t0.width,
                                           sum(t.channels for t in ts))
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in ts), t0.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in ts))


@register_vertex
@dataclasses.dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from_, to_] inclusive
    (nn/conf/graph/SubsetVertex.java)."""

    seq_parallelizable = True          # feature-axis slice

    from_: int = 0
    to_: int = 0

    def apply(self, inputs, *, mask=None):
        return inputs[0][..., self.from_:self.to_ + 1]

    def output_type(self, *ts: InputType) -> InputType:
        n = self.to_ - self.from_ + 1
        t = ts[0]
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)


@register_vertex
@dataclasses.dataclass
class StackVertex(GraphVertex):
    """Stack along batch axis (nn/conf/graph/StackVertex.java)."""

    def apply(self, inputs, *, mask=None):
        return jnp.concatenate(inputs, axis=0)

    def propagate_mask(self, in_masks, inputs, mask_env=None):
        # reference StackVertex.java:165-194: vstack the masks; a
        # missing mask becomes all-ones with the present masks' width —
        # (B, T) for time series, (B, 1) for feed-forward inputs.
        # 1-D (B,) masks are normalized to (B, 1) first so every row
        # of the concat has rank 2.
        if all(m is None for m in in_masks):
            return None
        norm = [None if m is None
                else (m[:, None] if m.ndim == 1 else m)
                for m in in_masks]
        width = next(m.shape[1] for m in norm if m is not None)
        mats = []
        for m, x in zip(norm, inputs):
            if m is not None:
                mats.append(m)
            elif x.ndim == 3:
                mats.append(jnp.ones(x.shape[:2], dtype=jnp.float32))
            else:
                mats.append(jnp.ones((x.shape[0], width),
                                     dtype=jnp.float32))
        return jnp.concatenate(mats, axis=0)


@register_vertex
@dataclasses.dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``from_`` of ``stack_size`` along batch
    (nn/conf/graph/UnstackVertex.java)."""

    from_: int = 0
    stack_size: int = 1

    def apply(self, inputs, *, mask=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_ * step:(self.from_ + 1) * step]

    def propagate_mask(self, in_masks, inputs, mask_env=None):
        m = in_masks[0]
        if m is None:
            return None
        step = m.shape[0] // self.stack_size
        return m[self.from_ * step:(self.from_ + 1) * step]


@register_vertex
@dataclasses.dataclass
class ScaleVertex(GraphVertex):
    """(nn/conf/graph/ScaleVertex.java)."""

    seq_parallelizable = True          # elementwise

    scale: float = 1.0

    def apply(self, inputs, *, mask=None):
        return inputs[0] * self.scale


@register_vertex
@dataclasses.dataclass
class ShiftVertex(GraphVertex):
    """(nn/conf/graph/ShiftVertex.java)."""

    seq_parallelizable = True          # elementwise

    shift: float = 0.0

    def apply(self, inputs, *, mask=None):
        return inputs[0] + self.shift


@register_vertex
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over feature axes (nn/conf/graph/L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, inputs, *, mask=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (n + self.eps)


@register_vertex
@dataclasses.dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs
    (nn/conf/graph/L2Vertex.java) → (B,1)."""

    eps: float = 1e-8

    def apply(self, inputs, *, mask=None):
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=axes, keepdims=False)
                        + self.eps)[:, None]

    def output_type(self, *ts: InputType) -> InputType:
        return InputType.feed_forward(1)


@register_vertex
@dataclasses.dataclass
class PreprocessorVertex(GraphVertex):
    """Wraps an InputPreProcessor (nn/conf/graph/PreprocessorVertex.java)."""

    preprocessor: Optional[dict] = None

    def _pp(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import (
            preprocessor_from_dict)
        return preprocessor_from_dict(self.preprocessor)

    def apply(self, inputs, *, mask=None):
        return self._pp()(inputs[0])

    def output_type(self, *ts: InputType) -> InputType:
        return self._pp().output_type(ts[0])


@register_vertex
@dataclasses.dataclass
class ReshapeVertex(GraphVertex):
    """(nn/conf/graph/ReshapeVertex.java). Shape excludes batch dim."""

    shape: Tuple[int, ...] = ()

    def apply(self, inputs, *, mask=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_vertex
@dataclasses.dataclass
class PoolHelperVertex(GraphVertex):
    """Strips the first row/col of a CNN activation — GoogLeNet
    compatibility shim (nn/conf/graph/PoolHelperVertex.java)."""

    def apply(self, inputs, *, mask=None):
        return inputs[0][:, 1:, 1:, :]

    def output_type(self, *ts: InputType) -> InputType:
        t = ts[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)


@register_vertex
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertex):
    """Last unmasked timestep of a (B,T,C) input
    (nn/conf/graph/rnn/LastTimeStepVertex.java). ``mask_input`` names
    the graph input whose mask applies."""

    mask_input: Optional[str] = None

    def apply(self, inputs, *, mask=None):
        x = inputs[0]
        if mask is None:
            return x[:, -1, :]
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]

    def propagate_mask(self, in_masks, inputs, mask_env=None):
        # after extracting the last step the mask is consumed
        # (reference rnn/LastTimeStepVertex.java:144-149)
        return None

    def output_type(self, *ts: InputType) -> InputType:
        return InputType.feed_forward(ts[0].size)


@register_vertex
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """Broadcast a (B,C) vector across T timesteps of a reference input
    (nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java). The second
    input supplies T."""

    ts_input: Optional[str] = None

    def apply(self, inputs, *, mask=None):
        x, ref = inputs[0], inputs[1]
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], ref.shape[1], x.shape[1]))

    def propagate_mask(self, in_masks, inputs, mask_env=None):
        # present as per the corresponding time-series input's mask
        # (reference rnn/DuplicateToTimeSeriesVertex.java:104-113)
        if self.ts_input is not None and mask_env is not None:
            return mask_env.get(self.ts_input)
        return None

    def output_type(self, *ts: InputType) -> InputType:
        return InputType.recurrent(ts[0].flat_size(),
                                   ts[1].timesteps if len(ts) > 1 else None)
