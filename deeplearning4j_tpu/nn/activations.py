"""Activation function registry.

Mirrors the reference's activation vocabulary (ND4J ``Activation`` enum,
referenced from nn/conf/layers/*.java builder ``activation(...)``), as a
name → pure-jax function table. All functions are elementwise (softmax
excepted) and jit/grad-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["get", "register", "ACTIVATIONS", "softmax"]


def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def _rational_tanh(x):
    # Rational tanh approximation (ND4J RationalTanh):
    # f(x) = 1.7159 * tanh_approx(2x/3), tanh_approx via a Padé-like form.
    a = 2.0 * x / 3.0
    aa = jnp.abs(a)
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + aa + a * a + 1.41645 * a ** 4))
    return 1.7159 * approx


def _rectified_tanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))

def _hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


ACTIVATIONS = {
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": _hard_sigmoid,
    "tanh": jnp.tanh,
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "rationaltanh": _rational_tanh,
    "rectifiedtanh": _rectified_tanh,
    "softmax": softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "cube": lambda x: x ** 3,
    "threshold": lambda x: (x > 0).astype(x.dtype),
}


def register(name: str, fn) -> None:
    ACTIVATIONS[name.lower()] = fn


def get(name):
    """Resolve an activation by name (or pass through a callable)."""
    if callable(name):
        return name
    key = str(name).lower().replace("_", "")
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}"
        )
    return ACTIVATIONS[key]
