"""Layer-context error wrapping for executor forward/fit.

The reference names the failing layer in config- and runtime-errors
(e.g. shape checks in InputTypeUtil and per-layer validation in
MultiLayerNetwork.init). In JAX, a wrong input shape surfaces at trace
time as a long framework traceback with no hint of WHICH layer the
mismatch hit — these helpers annotate the failure with the layer
index/name, its class, and the offending input shape before the XLA
detail."""

from __future__ import annotations

from contextlib import contextmanager

__all__ = ["NetworkExecutionError", "layer_error_context"]


class NetworkExecutionError(ValueError):
    """A forward/fit failure annotated with the failing layer."""


@contextmanager
def layer_error_context(where: str, layer, x=None):
    """Re-raise any trace-time failure inside a layer apply with the
    layer named. ``where``: e.g. "layer 3" or "vertex 'merge'"."""
    try:
        yield
    except NetworkExecutionError:
        raise                      # already annotated (nested graphs)
    except Exception as e:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        desc = type(layer).__name__
        name = getattr(layer, "name", None)
        if name:
            desc += f" '{name}'"
        got = (f" with input shape {tuple(shape)} ({dtype})"
               if shape is not None else "")
        raise NetworkExecutionError(
            f"Error executing {where} ({desc}){got}: "
            f"{type(e).__name__}: {e}") from e
