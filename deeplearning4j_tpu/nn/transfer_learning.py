"""Transfer learning: network surgery on trained models.

Mirrors nn/transferlearning/TransferLearning.java: freeze layers below
a boundary (``set_feature_extractor``, reference :84 — wraps them in
FrozenLayer), replace a layer's n_out with re-initialized weights
(``n_out_replace``, :98), remove/add output layers, and apply a
``FineTuneConfiguration`` (new global updater/lr for the unfrozen part).

Works on MultiLayerNetwork; graph surgery (TransferLearning.GraphBuilder)
operates on ComputationGraph by vertex name.
"""

from __future__ import annotations

import copy
import logging
from typing import List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["TransferLearning", "FineTuneConfiguration"]


class FineTuneConfiguration:
    """(nn/transferlearning/FineTuneConfiguration.java): overrides
    applied to the *unfrozen* part of the network."""

    def __init__(self, updater: Optional[dict] = None,
                 seed: Optional[int] = None,
                 dropout: Optional[float] = None):
        self.updater = updater
        self.seed = seed
        self.dropout = dropout


class TransferLearning:
    """Builder (nn/transferlearning/TransferLearning.java Builder)."""

    def __init__(self, net: MultiLayerNetwork):
        if net.params is None:
            raise ValueError("Transfer learning requires an initialized net")
        self._src = net
        self._freeze_until: Optional[int] = None
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._nout_replacements = {}       # idx -> (n_out, weight_init)
        self._remove_last = 0
        self._appended: List[Layer] = []

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning":
        return TransferLearning(net)

    def fine_tune_configuration(self, cfg: FineTuneConfiguration):
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, layer_idx: int):
        """Freeze layers [0..layer_idx] (reference :84)."""
        self._freeze_until = layer_idx
        return self

    def n_out_replace(self, layer_idx: int, n_out: int,
                      weight_init: str = "xavier"):
        self._nout_replacements[layer_idx] = (n_out, weight_init)
        return self

    def remove_output_layer(self):
        self._remove_last += 1
        return self

    def remove_layers_from_output(self, n: int):
        self._remove_last += n
        return self

    def add_layer(self, layer: Layer):
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._src
        conf_dict = src.conf.to_dict()
        new_conf = MultiLayerConfiguration.from_dict(conf_dict)
        layers = new_conf.layers
        from deeplearning4j_tpu.util.tree import tree_copy
        params = tree_copy(src.params)
        states = tree_copy(src.state)

        # 1. remove output layers
        for _ in range(self._remove_last):
            layers.pop()
            params.pop()
            states.pop()
            new_conf.preprocessors.pop(len(layers), None)

        # 2. append new layers (shapes inferred below at init of new ones)
        layers.extend(self._appended)

        # 3. apply fine-tune overrides
        if self._fine_tune is not None:
            if self._fine_tune.updater is not None:
                new_conf.conf.updater_cfg = self._fine_tune.updater
            if self._fine_tune.seed is not None:
                new_conf.conf.seed = self._fine_tune.seed
            if self._fine_tune.dropout is not None:
                # applies to layers that will remain trainable (frozen
                # layers run inference-mode anyway)
                start = (self._freeze_until + 1
                         if self._freeze_until is not None else 0)
                for lay in layers[start:]:
                    lay.dropout = self._fine_tune.dropout

        # 4. wrap frozen layers
        if self._freeze_until is not None:
            for i in range(self._freeze_until + 1):
                if not isinstance(layers[i], FrozenLayer):
                    layers[i] = FrozenLayer(inner=layers[i])

        # 5. rebuild net; re-init then copy/transplant params
        net = MultiLayerNetwork(new_conf)
        net.init(new_conf.conf.seed)
        n_copied = len(params)
        for i in range(len(layers)):
            if i in self._nout_replacements:
                continue                  # keep fresh init
            if i < n_copied:
                net.params[i] = params[i]
                net.state[i] = states[i]

        # 6. n_out replacement: re-init that layer AND the next (its
        #    n_in changed), reference nOutReplace semantics
        if self._nout_replacements:
            t = new_conf.input_type
            key = jax.random.PRNGKey(new_conf.conf.seed or 0)
            for idx, (n_out, w_init) in self._nout_replacements.items():
                lay = layers[idx]
                target = lay.wrapped if isinstance(lay, FrozenLayer) else lay
                target.n_out = n_out
                target.weight_init = w_init
            # recompute shapes & re-init affected layers
            t = new_conf.input_type
            for i, lay in enumerate(layers):
                if t is not None and i in new_conf.preprocessors:
                    t = new_conf.preprocessors[i].output_type(t)
                affected = (i in self._nout_replacements
                            or (i - 1) in self._nout_replacements)
                if affected:
                    target = lay.wrapped if isinstance(lay, FrozenLayer) \
                        else lay
                    if hasattr(target, "n_in"):
                        target.n_in = None
                    p, s = lay.initialize(jax.random.fold_in(key, i), t)
                    net.params[i] = p
                    net.state[i] = s
                elif t is not None:
                    lay.set_n_in(t)
                t = lay.output_type(t) if t is not None else None

        net._build_optimizer()
        return net
