"""Transfer learning: network surgery on trained models.

Mirrors nn/transferlearning/TransferLearning.java: freeze layers below
a boundary (``set_feature_extractor``, reference :84 — wraps them in
FrozenLayer), replace a layer's n_out with re-initialized weights
(``n_out_replace``, :98), remove/add output layers, and apply a
``FineTuneConfiguration`` (new global updater/lr for the unfrozen part).

``TransferLearning`` operates on MultiLayerNetwork;
``TransferLearningGraph`` is the vertex-name surgery builder for
ComputationGraph (reference TransferLearning.GraphBuilder :449:
setFeatureExtractor :501 freezes the named vertices and every vertex
on a path from an input to them, nOutReplace :520, removeVertex
:631/:642, addLayer/addVertex :655/:685, setOutputs :698).
"""

from __future__ import annotations

import copy
import logging
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.layers.base import Layer
from deeplearning4j_tpu.nn.conf.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["TransferLearning", "TransferLearningGraph",
           "FineTuneConfiguration"]


class FineTuneConfiguration:
    """(nn/transferlearning/FineTuneConfiguration.java): overrides
    applied to the *unfrozen* part of the network."""

    def __init__(self, updater: Optional[dict] = None,
                 seed: Optional[int] = None,
                 dropout: Optional[float] = None):
        self.updater = updater
        self.seed = seed
        self.dropout = dropout


class TransferLearning:
    """Builder (nn/transferlearning/TransferLearning.java Builder)."""

    def __init__(self, net: MultiLayerNetwork):
        if net.params is None:
            raise ValueError("Transfer learning requires an initialized net")
        self._src = net
        self._freeze_until: Optional[int] = None
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._nout_replacements = {}       # idx -> (n_out, weight_init)
        self._remove_last = 0
        self._appended: List[Layer] = []

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning":
        return TransferLearning(net)

    def fine_tune_configuration(self, cfg: FineTuneConfiguration):
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, layer_idx: int):
        """Freeze layers [0..layer_idx] (reference :84)."""
        self._freeze_until = layer_idx
        return self

    def n_out_replace(self, layer_idx: int, n_out: int,
                      weight_init: str = "xavier"):
        self._nout_replacements[layer_idx] = (n_out, weight_init)
        return self

    def remove_output_layer(self):
        self._remove_last += 1
        return self

    def remove_layers_from_output(self, n: int):
        self._remove_last += n
        return self

    def add_layer(self, layer: Layer):
        self._appended.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        src = self._src
        conf_dict = src.conf.to_dict()
        new_conf = MultiLayerConfiguration.from_dict(conf_dict)
        layers = new_conf.layers
        from deeplearning4j_tpu.util.tree import tree_copy
        params = tree_copy(src.params)
        states = tree_copy(src.state)

        # 1. remove output layers
        for _ in range(self._remove_last):
            layers.pop()
            params.pop()
            states.pop()
            new_conf.preprocessors.pop(len(layers), None)

        # 2. append new layers (shapes inferred below at init of new ones)
        layers.extend(self._appended)

        # 3. apply fine-tune overrides
        if self._fine_tune is not None:
            if self._fine_tune.updater is not None:
                new_conf.conf.updater_cfg = self._fine_tune.updater
            if self._fine_tune.seed is not None:
                new_conf.conf.seed = self._fine_tune.seed
            if self._fine_tune.dropout is not None:
                # applies to layers that will remain trainable (frozen
                # layers run inference-mode anyway)
                start = (self._freeze_until + 1
                         if self._freeze_until is not None else 0)
                for lay in layers[start:]:
                    lay.dropout = self._fine_tune.dropout

        # 4. wrap frozen layers
        if self._freeze_until is not None:
            for i in range(self._freeze_until + 1):
                if not isinstance(layers[i], FrozenLayer):
                    layers[i] = FrozenLayer(inner=layers[i])

        # 5. rebuild net; re-init then copy/transplant params
        net = MultiLayerNetwork(new_conf)
        net.init(new_conf.conf.seed)
        n_copied = len(params)
        for i in range(len(layers)):
            if i in self._nout_replacements:
                continue                  # keep fresh init
            if i < n_copied:
                net.params[i] = params[i]
                net.state[i] = states[i]

        # 6. n_out replacement: re-init that layer AND the next (its
        #    n_in changed), reference nOutReplace semantics
        if self._nout_replacements:
            t = new_conf.input_type
            key = jax.random.PRNGKey(new_conf.conf.seed or 0)
            for idx, (n_out, w_init) in self._nout_replacements.items():
                lay = layers[idx]
                target = lay.wrapped if isinstance(lay, FrozenLayer) else lay
                target.n_out = n_out
                target.weight_init = w_init
            # recompute shapes & re-init affected layers
            t = new_conf.input_type
            for i, lay in enumerate(layers):
                if t is not None and i in new_conf.preprocessors:
                    t = new_conf.preprocessors[i].output_type(t)
                affected = (i in self._nout_replacements
                            or (i - 1) in self._nout_replacements)
                if affected:
                    target = lay.wrapped if isinstance(lay, FrozenLayer) \
                        else lay
                    if hasattr(target, "n_in"):
                        target.n_in = None
                    p, s = lay.initialize(jax.random.fold_in(key, i), t)
                    net.params[i] = p
                    net.state[i] = s
                elif t is not None:
                    lay.set_n_in(t)
                t = lay.output_type(t) if t is not None else None

        net._build_optimizer()
        return net


class TransferLearningGraph:
    """Vertex-name surgery on a trained ComputationGraph (reference
    TransferLearning.GraphBuilder, TransferLearning.java:449)."""

    def __init__(self, cg):
        if cg.params is None:
            raise ValueError("Transfer learning requires an initialized "
                             "graph")
        self._src = cg
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._frozen_at: List[str] = []
        self._nout_replacements: Dict[str, Tuple[int, str]] = {}
        self._removed: List[Tuple[str, bool]] = []   # (name, keep_conns)
        self._added: List[Tuple[str, object, List[str]]] = []
        self._new_outputs: Optional[List[str]] = None

    @staticmethod
    def builder(cg) -> "TransferLearningGraph":
        return TransferLearningGraph(cg)

    def fine_tune_configuration(self, cfg: FineTuneConfiguration):
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, *vertex_names: str):
        """Freeze the named vertices and every vertex on a path from an
        input to them (reference :501)."""
        self._frozen_at.extend(vertex_names)
        return self

    def n_out_replace(self, layer_name: str, n_out: int,
                      weight_init: str = "xavier"):
        """Change a layer vertex's n_out; the vertex AND its direct
        consumers are re-initialized (reference :520 — 'this will also
        affect the vertex layer that follows')."""
        self._nout_replacements[layer_name] = (n_out, weight_init)
        return self

    def remove_vertex_keep_connections(self, name: str):
        """Remove the vertex definition; downstream wiring referencing
        ``name`` is kept, expecting a new vertex added under the same
        name (reference removeVertexKeepConnections :631)."""
        self._removed.append((name, True))
        return self

    def remove_vertex_and_connections(self, name: str):
        """Remove the vertex and prune it from every consumer's input
        list (reference removeVertexAndConnections :642)."""
        self._removed.append((name, False))
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str):
        self._added.append((name, layer, list(inputs)))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        self._added.append((name, vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str):
        self._new_outputs = list(names)
        return self

    # ------------------------------------------------------------------
    @staticmethod
    def _propagate_width_change(vertices, seed: str, affected: set):
        """Mark every vertex whose input width changes when ``seed``'s
        output width changes: direct consumers, and (transitively)
        consumers of parameter-less vertices, which pass width through."""
        frontier = [seed]
        seen = {seed}
        while frontier:
            cur = frontier.pop()
            for vname, (obj, ins) in vertices.items():
                if cur in ins and vname not in seen:
                    seen.add(vname)
                    affected.add(vname)
                    if not isinstance(obj, Layer):
                        frontier.append(vname)

    def _ancestors_inclusive(self, vertices, targets):
        """The named vertices plus everything upstream of them."""
        out = set()
        stack = [t for t in targets]
        while stack:
            n = stack.pop()
            if n in out or n not in vertices:
                continue
            out.add(n)
            stack.extend(vertices[n][1])
        return out

    def build(self):
        from deeplearning4j_tpu.models.computation_graph import (
            ComputationGraph)
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.util.tree import tree_copy

        src = self._src
        conf = src.conf.clone()
        vertices = conf.vertices           # name -> (obj, ins)
        outputs = list(conf.network_outputs)

        # 1. removals; consumers of a pruned vertex see a width change
        rewired = set()
        removed_output_pos = {}
        for name, keep in self._removed:
            if name not in vertices:
                raise ValueError(f"Cannot remove unknown vertex '{name}'")
            del vertices[name]
            if not keep:
                for vname, (obj, ins) in list(vertices.items()):
                    if name in ins:
                        vertices[vname] = (obj,
                                           [i for i in ins if i != name])
                        rewired.add(vname)
            if name in outputs:
                removed_output_pos[name] = outputs.index(name)
                outputs = [o for o in outputs if o != name]

        # 2. additions (stamp global defaults like GraphBuilder.add_layer);
        #    re-adding a vertex under a removed output's name restores
        #    its output slot (the remove-head/add-head fine-tune flow)
        added_names = set()
        for name, obj, ins in self._added:
            if isinstance(obj, Layer):
                obj = conf.conf.stamp_defaults(obj)
                obj.name = name
            vertices[name] = (obj, list(ins))
            added_names.add(name)
            if name in removed_output_pos and name not in outputs:
                outputs.insert(min(removed_output_pos[name],
                                   len(outputs)), name)

        # 3. outputs
        if self._new_outputs is not None:
            outputs = list(self._new_outputs)

        # 4. fine-tune overrides
        if self._fine_tune is not None:
            if self._fine_tune.updater is not None:
                conf.conf.updater_cfg = self._fine_tune.updater
            if self._fine_tune.seed is not None:
                conf.conf.seed = self._fine_tune.seed

        # 5. n_out replacement: mutate the named layers; mark them and
        #    their direct consumers for re-init. Rewired vertices
        #    (pruned inputs) are width-change sources too.
        affected = set(added_names)
        for vname in rewired:
            obj2, _ = vertices[vname]
            affected.add(vname)
            if not isinstance(obj2, Layer):
                # parameter-less vertex: width change propagates to
                # its consumers
                self._propagate_width_change(vertices, vname, affected)
        for lname, (n_out, w_init) in self._nout_replacements.items():
            if lname not in vertices:
                raise ValueError(f"n_out_replace: unknown vertex "
                                 f"'{lname}'")
            obj, ins = vertices[lname]
            target = obj.wrapped if isinstance(obj, FrozenLayer) else obj
            if not isinstance(target, Layer):
                raise ValueError(f"n_out_replace: '{lname}' is not a "
                                 f"layer vertex")
            target.n_out = n_out
            target.weight_init = w_init
            affected.add(lname)
            # direct consumers change input width; a parameter-less
            # vertex (Merge/ElementWise/...) passes the width change on
            # to ITS consumers
            self._propagate_width_change(vertices, lname, affected)

        # 6. reset shape inference for affected vertices so the new
        #    widths propagate (set_n_in only fills n_in when unset)
        for vname in affected:
            obj, _ = vertices.get(vname, (None, None))
            if obj is None:
                continue
            target = obj.wrapped if isinstance(obj, FrozenLayer) else obj
            if hasattr(target, "n_in"):
                target.n_in = None

        # 7. freeze: named vertices + all their ancestors. Validate the
        #    names — a typo must not silently freeze nothing and let
        #    fine-tuning destroy the pretrained stem
        for name in self._frozen_at:
            if name not in vertices:
                raise ValueError(
                    f"set_feature_extractor: unknown vertex '{name}' "
                    f"(have {sorted(vertices)})")
        frozen = self._ancestors_inclusive(vertices, self._frozen_at)
        for vname in frozen:
            obj, ins = vertices[vname]
            if isinstance(obj, Layer) and not isinstance(obj, FrozenLayer):
                vertices[vname] = (FrozenLayer(inner=obj), ins)

        new_conf = ComputationGraphConfiguration(
            conf.conf, conf.network_inputs, vertices, outputs,
            conf.input_types)
        cg = ComputationGraph(new_conf)
        cg.init(new_conf.conf.seed)

        # 8. transplant surviving params (everything except affected)
        for vname in cg.params:
            if vname in affected:
                continue
            if src.params is not None and vname in src.params:
                cg.params[vname] = tree_copy(src.params[vname])
                cg.state[vname] = tree_copy(src.state[vname])
        cg._build_optimizer()
        return cg
