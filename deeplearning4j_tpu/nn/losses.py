"""Loss function registry.

Mirrors the reference's ``LossFunctions.LossFunction`` vocabulary (used
by nn/conf/layers/OutputLayer via ``lossFunction(...)``). Every loss is
``fn(labels, preds, mask) -> per-example score`` averaged to a scalar by
the caller; ``mask`` is an optional broadcastable 0/1 array (the
reference applies label masks inside ILossFunction.computeScoreArray).

Semantics follow the reference conventions:
- losses are computed on *post-activation* output (e.g. MCXENT expects
  softmax output, XENT expects sigmoid output), matching DL4J where the
  output layer applies its activation then the loss. Fused stable paths
  (softmax+CE) are used internally when the layer knows its activation.
- per-output scores are *summed over the output dimension* and averaged
  over examples (DL4J divides the total score by #examples).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["get", "register", "LOSSES", "score"]

_EPS = 1e-10


def _reduce(per_output, mask):
    # sum over feature axes -> per-example score
    if mask is not None:
        per_output = per_output * mask
    axes = tuple(range(1, per_output.ndim))
    return jnp.sum(per_output, axis=axes)


def mcxent(labels, preds, mask=None):
    """Multi-class cross entropy against probabilities (post-softmax)."""
    return _reduce(-labels * jnp.log(preds + _EPS), mask)


def negativeloglikelihood(labels, preds, mask=None):
    return mcxent(labels, preds, mask)


def xent(labels, preds, mask=None):
    """Binary cross entropy (post-sigmoid), summed over outputs."""
    p = jnp.clip(preds, _EPS, 1.0 - _EPS)
    return _reduce(-(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p)), mask)


def mse(labels, preds, mask=None):
    # DL4J SQUARED_LOSS: mean over output dim of squared error
    d = (preds - labels) ** 2
    n = d.shape[-1]
    return _reduce(d, mask) / n


def l2(labels, preds, mask=None):
    return _reduce((preds - labels) ** 2, mask)


def mae(labels, preds, mask=None):
    d = jnp.abs(preds - labels)
    return _reduce(d, mask) / d.shape[-1]


def l1(labels, preds, mask=None):
    return _reduce(jnp.abs(preds - labels), mask)


def hinge(labels, preds, mask=None):
    # labels in {-1, +1} or {0,1} (converted)
    y = jnp.where(labels > 0, 1.0, -1.0)
    return _reduce(jnp.maximum(0.0, 1.0 - y * preds), mask)


def squared_hinge(labels, preds, mask=None):
    y = jnp.where(labels > 0, 1.0, -1.0)
    return _reduce(jnp.maximum(0.0, 1.0 - y * preds) ** 2, mask)


def kl_divergence(labels, preds, mask=None):
    p = jnp.clip(preds, _EPS, 1.0)
    t = jnp.clip(labels, _EPS, 1.0)
    return _reduce(labels * (jnp.log(t) - jnp.log(p)), mask)


def poisson(labels, preds, mask=None):
    return _reduce(preds - labels * jnp.log(preds + _EPS), mask)


def cosine_proximity(labels, preds, mask=None):
    if mask is not None:
        labels = labels * mask
        preds = preds * mask
    ln = jnp.linalg.norm(labels, axis=-1)
    pn = jnp.linalg.norm(preds, axis=-1)
    dot = jnp.sum(labels * preds, axis=-1)
    out = -dot / (ln * pn + _EPS)
    axes = tuple(range(1, out.ndim))
    return jnp.sum(out, axis=axes) if axes else out


def mean_squared_logarithmic_error(labels, preds, mask=None):
    d = (jnp.log1p(jnp.maximum(preds, -1 + _EPS)) - jnp.log1p(labels)) ** 2
    return _reduce(d, mask) / d.shape[-1]


def mean_absolute_percentage_error(labels, preds, mask=None):
    d = jnp.abs((labels - preds) / (jnp.abs(labels) + _EPS)) * 100.0
    return _reduce(d, mask) / d.shape[-1]


LOSSES = {
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "mse": mse,
    "squared_loss": mse,
    "l2": l2,
    "mae": mae,
    "l1": l1,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": xent,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "msle": mean_squared_logarithmic_error,
    "mape": mean_absolute_percentage_error,
}


def register(name: str, fn) -> None:
    LOSSES[name.lower()] = fn


def get(name):
    if callable(name):
        return name
    key = str(name).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(LOSSES)}")
    return LOSSES[key]


def score(name, labels, preds, mask=None, average: bool = True):
    """Total (or mean) score, DL4J-style: sum of per-example scores / N."""
    per_ex = get(name)(labels, preds, mask)
    return jnp.mean(per_ex) if average else jnp.sum(per_ex)
