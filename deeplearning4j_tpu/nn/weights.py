"""Weight initialization schemes.

Mirrors the reference's ``WeightInit`` enum + ``WeightInitUtil``
(deeplearning4j-nn nn/weights/WeightInit.java:54, WeightInitUtil.java)
and the distribution classes (nn/conf/distribution/*). Fan-in/fan-out
are computed from the *logical* layer geometry and passed in by the
param initializer, exactly as the reference does.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["init_weight", "distribution_sample", "WEIGHT_INITS"]


def init_weight(key, shape, scheme, fan_in, fan_out, *, distribution=None,
                dtype=jnp.float32):
    """Sample a weight array of ``shape`` under ``scheme``.

    ``scheme`` is a lower-case string from the WeightInit vocabulary, or
    'distribution' with a distribution config dict (see
    :func:`distribution_sample`).
    """
    s = str(scheme).lower()
    fan_in = max(float(fan_in), 1.0)
    fan_out = max(float(fan_out), 1.0)

    if s == "zero":
        return jnp.zeros(shape, dtype)
    if s == "ones":
        return jnp.ones(shape, dtype)
    if s == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if s == "normal":  # DL4J NORMAL: N(0, 1/sqrt(fan_in))
        return jax.random.normal(key, shape, dtype) / jnp.sqrt(fan_in)
    if s == "lecun_normal":
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(1.0 / fan_in)
    if s == "lecun_uniform":
        b = jnp.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -b, b)
    if s == "uniform":  # DL4J UNIFORM: U(-a, a), a = 1/sqrt(fan_in)
        a = 1.0 / jnp.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "xavier":  # N(0, 2 / (fan_in + fan_out))
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(
            2.0 / (fan_in + fan_out))
    if s == "xavier_uniform":  # U(-a, a), a = sqrt(6/(fan_in+fan_out))
        a = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) / jnp.sqrt(fan_in)
    if s == "xavier_legacy":
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(
            1.0 / (fan_in + fan_out))
    if s == "relu":  # He: N(0, 2/fan_in)
        return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in)
    if s == "relu_uniform":  # U(-a, a), a = sqrt(6/fan_in)
        a = jnp.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "sigmoid_uniform":
        a = 4.0 * jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s in ("var_scaling_normal_fan_in", "var_scaling_normal_fan_out",
             "var_scaling_normal_fan_avg", "var_scaling_uniform_fan_in",
             "var_scaling_uniform_fan_out", "var_scaling_uniform_fan_avg"):
        if s.endswith("fan_in"):
            n = fan_in
        elif s.endswith("fan_out"):
            n = fan_out
        else:
            n = 0.5 * (fan_in + fan_out)
        if "normal" in s:
            return jax.random.normal(key, shape, dtype) * jnp.sqrt(1.0 / n)
        a = jnp.sqrt(3.0 / n)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if s == "distribution":
        if distribution is None:
            raise ValueError("WeightInit 'distribution' requires a "
                             "distribution config")
        return distribution_sample(key, shape, distribution, dtype=dtype)
    raise ValueError(f"Unknown weight init scheme '{scheme}'")


WEIGHT_INITS = [
    "zero", "ones", "identity", "normal", "lecun_normal", "lecun_uniform",
    "uniform", "xavier", "xavier_uniform", "xavier_fan_in", "xavier_legacy",
    "relu", "relu_uniform", "sigmoid_uniform", "distribution",
    "var_scaling_normal_fan_in", "var_scaling_normal_fan_out",
    "var_scaling_normal_fan_avg", "var_scaling_uniform_fan_in",
    "var_scaling_uniform_fan_out", "var_scaling_uniform_fan_avg",
]


def distribution_sample(key, shape, dist, *, dtype=jnp.float32):
    """Sample from a distribution config dict.

    Mirrors nn/conf/distribution/*: ``{"type": "normal"|"gaussian",
    "mean": m, "std": s}``, ``{"type": "uniform", "lower": a, "upper": b}``,
    ``{"type": "binomial", "n": n, "p": p}``,
    ``{"type": "truncated_normal", ...}``, ``{"type": "constant", ...}``,
    ``{"type": "log_normal", ...}``, ``{"type": "orthogonal", "gain": g}``.
    """
    t = str(dist.get("type", "normal")).lower()
    if t in ("normal", "gaussian"):
        return (dist.get("mean", 0.0)
                + dist.get("std", 1.0) * jax.random.normal(key, shape, dtype))
    if t == "uniform":
        return jax.random.uniform(key, shape, dtype,
                                  dist.get("lower", 0.0),
                                  dist.get("upper", 1.0))
    if t == "binomial":
        p = dist.get("p", 0.5)
        n = int(dist.get("n", 1))
        return jax.random.binomial(
            key, n, p, shape=shape).astype(dtype)
    if t == "truncated_normal":
        std = dist.get("std", 1.0)
        mean = dist.get("mean", 0.0)
        return mean + std * jax.random.truncated_normal(key, -2.0, 2.0,
                                                        shape, dtype)
    if t == "constant":
        return jnp.full(shape, dist.get("value", 0.0), dtype)
    if t == "log_normal":
        return jnp.exp(dist.get("mean", 0.0)
                       + dist.get("std", 1.0)
                       * jax.random.normal(key, shape, dtype))
    if t == "orthogonal":
        return _orthogonal(key, shape, dist.get("gain", 1.0), dtype)
    raise ValueError(f"Unknown distribution type '{t}'")


def _orthogonal(key, shape, gain, dtype):
    n_rows = shape[0]
    n_cols = 1
    for d in shape[1:]:
        n_cols *= d
    flat = (max(n_rows, n_cols), min(n_rows, n_cols))
    a = jax.random.normal(key, flat, jnp.float32)
    q, r = jnp.linalg.qr(a)
    q = q * jnp.sign(jnp.diagonal(r))
    if n_rows < n_cols:
        q = q.T
    return (gain * q[:n_rows, :n_cols].reshape(shape)).astype(dtype)
