"""Gradient checking — central-difference vs autodiff.

Mirrors gradientcheck/GradientCheckUtil.java:48,106 (the backbone of the
reference's test strategy, SURVEY §4.1): numerical gradient
(C(w+ε) − C(w−ε)) / 2ε compared against the analytic gradient for every
parameter. Where the reference checks hand-written backpropGradient
implementations, here it validates the whole loss pipeline (layer math,
masking, regularization, fused CE paths) against ``jax.grad`` — which
catches wrong *forward* math (e.g. a mis-fused stable-softmax) that
plain unit tests miss.

Runs in float64 on CPU (jax_enable_x64 inside the check) with tiny nets,
like the reference's double-precision gradient-check configs.
"""

from __future__ import annotations

import logging
from typing import Optional

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import dtypes

logger = logging.getLogger("deeplearning4j_tpu")


@contextlib.contextmanager
def _x64_policy():
    """f64 everywhere: jax x64 mode + an f64 dtype policy so layers
    (conv casts to the policy compute dtype) don't truncate to f32."""
    with jax.enable_x64(True):
        with dtypes.policy_scope(dtypes.Policy(jnp.float64, jnp.float64,
                                               jnp.float64)):
            yield

__all__ = ["check_gradients", "check_gradients_graph"]

DEFAULT_EPS = 1e-6
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


def _rel_error(a: float, n: float, min_abs: float) -> float:
    if abs(a - n) < min_abs:
        return 0.0
    denom = abs(a) + abs(n)
    return abs(a - n) / denom if denom > 0 else 0.0


def _run_check(loss_flat, flat0, eps, max_rel, min_abs, print_all):
    return _run_subset_check(loss_flat, np.asarray(flat0),
                             np.arange(np.asarray(flat0).shape[0]), eps,
                             max_rel, min_abs, print_all)


def check_gradients(net, ds, *, eps: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    print_all: bool = False,
                    subset: Optional[int] = None,
                    seed: int = 0) -> bool:
    """Check a MultiLayerNetwork's d(loss)/d(params).

    ``subset``: check only N randomly chosen parameters (the reference
    checks all; tiny nets keep 'all' feasible, subset makes larger
    configs tractable).
    """
    with _x64_policy():
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.params)
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.state)
        batch = tuple(
            None if a is None else jnp.asarray(np.asarray(a), jnp.float64)
            for a in net._batch_tuple(ds))

        leaves, treedef = jax.tree_util.tree_flatten(params64)
        sizes = [int(l.size) for l in leaves]
        shapes = [l.shape for l in leaves]
        flat0 = jnp.concatenate([l.ravel() for l in leaves])

        def unflatten(flat):
            out = []
            off = 0
            for sz, sh in zip(sizes, shapes):
                out.append(flat[off:off + sz].reshape(sh))
                off += sz
            return jax.tree_util.tree_unflatten(treedef, out)

        def loss_flat(flat):
            p = unflatten(flat)
            loss, _ = net._loss(p, state64, batch, None, training=False)
            return loss

        flat0 = np.asarray(flat0)
        if subset is not None and subset < flat0.shape[0]:
            idx = np.random.default_rng(seed).choice(
                flat0.shape[0], subset, replace=False)
            return _run_subset_check(loss_flat, flat0, idx, eps,
                                     max_rel_error, min_abs_error,
                                     print_all)
        return _run_check(loss_flat, flat0, eps, max_rel_error,
                          min_abs_error, print_all)


def _run_subset_check(loss_flat, flat0, idx, eps, max_rel, min_abs,
                      print_all):
    grad_analytic = np.asarray(jax.grad(loss_flat)(jnp.asarray(flat0)))
    fails = 0
    max_rel_seen = 0.0
    for i in idx:
        fp = np.array(flat0)
        fp[i] += eps
        fm = np.array(flat0)
        fm[i] -= eps
        num = (float(loss_flat(jnp.asarray(fp)))
               - float(loss_flat(jnp.asarray(fm)))) / (2 * eps)
        rel = _rel_error(float(grad_analytic[i]), num, min_abs)
        max_rel_seen = max(max_rel_seen, rel)
        if rel > max_rel:
            fails += 1
            if print_all or fails <= 10:
                logger.warning(
                    "param %d FAILED: analytic=%.8g numeric=%.8g rel=%.4g",
                    i, float(grad_analytic[i]), num, rel)
    logger.info("gradient check (%d params): %d failures, max rel %.4g",
                len(idx), fails, max_rel_seen)
    return fails == 0


def check_gradients_graph(cg, mds, *, eps: float = DEFAULT_EPS,
                          max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                          min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                          subset: Optional[int] = None,
                          seed: int = 0) -> bool:
    """Check a ComputationGraph (reference GradientCheckUtil :276)."""
    with _x64_policy():
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), cg.params)
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), cg.state)
        mds = cg._as_multi(mds)
        inputs = tuple(jnp.asarray(np.asarray(f), jnp.float64)
                       for f in mds.features)
        labels = tuple(jnp.asarray(np.asarray(l), jnp.float64)
                       for l in mds.labels)
        batch = (inputs, labels, None, None)

        leaves, treedef = jax.tree_util.tree_flatten(params64)
        sizes = [int(l.size) for l in leaves]
        shapes = [l.shape for l in leaves]
        flat0 = np.asarray(jnp.concatenate([l.ravel() for l in leaves]))

        def unflatten(flat):
            out = []
            off = 0
            for sz, sh in zip(sizes, shapes):
                out.append(flat[off:off + sz].reshape(sh))
                off += sz
            return jax.tree_util.tree_unflatten(treedef, out)

        def loss_flat(flat):
            p = unflatten(flat)
            loss, _ = cg._loss(p, state64, batch, None, training=False)
            return loss

        if subset is not None and subset < flat0.shape[0]:
            idx = np.random.default_rng(seed).choice(
                flat0.shape[0], subset, replace=False)
            return _run_subset_check(loss_flat, flat0, idx, eps,
                                     max_rel_error, min_abs_error, False)
        return _run_check(loss_flat, flat0, eps, max_rel_error,
                          min_abs_error, False)
