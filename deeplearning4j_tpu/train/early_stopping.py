"""Early stopping.

Mirrors earlystopping/**: EarlyStoppingConfiguration, termination
conditions (termination/*.java: MaxEpochsTerminationCondition,
MaxTimeIterationTerminationCondition, MaxScoreIterationTermination
Condition, ScoreImprovementEpochTerminationCondition,
InvalidScoreIterationTerminationCondition, BestScoreEpochTermination
Condition), model savers (saver/LocalFileModelSaver, InMemoryModelSaver)
and the trainer fit loop (trainer/BaseEarlyStoppingTrainer.java:76).

Score calculators mirror ScoreCalculator: default is loss on a test
iterator (DataSetLossCalculator).
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import math
import os
import time
from typing import Callable, List, Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult",
    "EarlyStoppingTrainer", "MaxEpochsTerminationCondition",
    "MaxTimeTerminationCondition", "MaxScoreTerminationCondition",
    "InvalidScoreTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition", "LocalFileModelSaver",
    "InMemoryModelSaver", "DataSetLossCalculator",
]


# ---------------------------------------------------------------------------
# termination conditions
# ---------------------------------------------------------------------------

class EpochTerminationCondition:
    requires_score = True      # False → checked even on unscored epochs

    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no score improvement
    (ScoreImprovementEpochTerminationCondition.java)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = math.inf
        self.epochs_without = 0

    def initialize(self):
        self.best = math.inf
        self.epochs_without = 0

    def terminate(self, epoch, score):
        if score < self.best - self.min_improvement:
            self.best = score
            self.epochs_without = 0
            return False
        self.epochs_without += 1
        return self.epochs_without > self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once score is at/below a target
    (BestScoreEpochTerminationCondition.java)."""

    def __init__(self, target_score: float):
        self.target = target_score

    def terminate(self, epoch, score):
        return score <= self.target


class MaxTimeTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self.start = None

    def initialize(self):
        self.start = time.time()

    def terminate(self, last_score):
        return (time.time() - self.start) > self.max_seconds


class MaxScoreTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score


class InvalidScoreTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)


# ---------------------------------------------------------------------------
# savers
# ---------------------------------------------------------------------------

class InMemoryModelSaver:
    """(saver/InMemoryModelSaver.java)."""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, model):
        from deeplearning4j_tpu.util.tree import tree_copy
        self.best = (tree_copy(model.params), tree_copy(model.state))

    def save_latest(self, model):
        from deeplearning4j_tpu.util.tree import tree_copy
        self.latest = (tree_copy(model.params), tree_copy(model.state))

    def restore_best(self, model):
        from deeplearning4j_tpu.util.tree import tree_copy
        if self.best is not None:
            # copy again: a later fit() donates model buffers and would
            # otherwise delete the saved snapshot
            model.params, model.state = tree_copy(self.best)
        return model


class LocalFileModelSaver:
    """(saver/LocalFileModelSaver.java): bestModel.zip / latestModel.zip."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def save_best(self, model):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, os.path.join(self.directory, "bestModel.zip"))

    def save_latest(self, model):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, os.path.join(self.directory, "latestModel.zip"))

    def restore_best(self, model):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(os.path.join(self.directory, "bestModel.zip"))


# ---------------------------------------------------------------------------
# score calculators
# ---------------------------------------------------------------------------

class DataSetLossCalculator:
    """Average loss over a held-out iterator
    (scorecalc/DataSetLossCalculator.java)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total = 0.0
        n = 0
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if (self.average and n) else total


# ---------------------------------------------------------------------------
# config + result + trainer
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[EpochTerminationCondition] = \
        dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    score_calculator: Optional[object] = None
    model_saver: object = dataclasses.field(
        default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str            # 'epoch' | 'iteration' | 'error'
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: object


class EarlyStoppingTrainer:
    """(trainer/BaseEarlyStoppingTrainer.java:76 fit loop)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.config = config
        self.model = model
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        model = self.model
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        if model.params is None:
            model.init()

        best_score = math.inf
        best_epoch = -1
        score_vs_epoch = {}
        epoch = 0
        reason, details = "epoch", "max epochs"

        class _IterationGuard:
            """Listener that raises to stop mid-epoch on iteration
            conditions (reference checks per-minibatch)."""
            class Stop(Exception):
                def __init__(self, cond):
                    self.cond = cond

            def __init__(self, conds):
                self.conds = conds

            def on_epoch_start(self, m):
                pass

            def on_epoch_end(self, m):
                pass

            def iteration_done(self, m, it, score, bs):
                s = float(score)
                for c in self.conds:
                    if c.terminate(s):
                        raise _IterationGuard.Stop(c)

        guard = _IterationGuard(cfg.iteration_termination_conditions)
        saved_listeners = list(model.listeners)
        model.listeners = saved_listeners + [guard]
        try:
            while True:
                try:
                    model.fit(self.train_iterator, epochs=1)
                except _IterationGuard.Stop as stop:
                    reason = "iteration"
                    details = type(stop.cond).__name__
                    break
                # score this epoch; with a score calculator, epochs it
                # skips are NOT scored at all (mixing train loss into
                # best-model selection would compare different metrics —
                # reference BaseEarlyStoppingTrainer skips them too)
                score = None
                if cfg.score_calculator is not None:
                    if epoch % cfg.evaluate_every_n_epochs == 0:
                        score = float(
                            cfg.score_calculator.calculate_score(model))
                else:
                    score = float(model.score_value)
                if score is not None:
                    score_vs_epoch[epoch] = score
                    if score < best_score:
                        best_score = score
                        best_epoch = epoch
                        cfg.model_saver.save_best(model)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(model)
                stop_now = False
                for c in cfg.epoch_termination_conditions:
                    if score is None and c.requires_score:
                        continue
                    if c.terminate(epoch, score):
                        reason = "epoch"
                        details = type(c).__name__
                        stop_now = True
                        break
                epoch += 1
                if stop_now:
                    break
        finally:
            model.listeners = saved_listeners

        best_model = cfg.model_saver.restore_best(model) \
            if best_epoch >= 0 else model
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch, best_model_epoch=best_epoch,
            best_model_score=best_score, score_vs_epoch=score_vs_epoch,
            best_model=best_model)
