"""Training listeners.

Mirrors optimize/api/IterationListener.java + TrainingListener.java and
the impls in optimize/listeners/**: ScoreIterationListener,
PerformanceListener (samples/sec, batches/sec,
PerformanceListener.java:97-119), EvaluativeListener,
CollectScoresIterationListener, TimeIterationListener,
SleepyTrainingListener (debug throttle), CheckpointListener.

Listeners run on host between jitted steps; the executor calls
``iteration_done`` with the (device) scalar score — listeners that read
it force a sync, so throughput-sensitive ones (Performance) only touch
it every ``frequency`` iterations.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["TrainingListener", "ScoreIterationListener",
           "PerformanceListener", "CollectScoresIterationListener",
           "TimeIterationListener", "EvaluativeListener",
           "SleepyTrainingListener", "CheckpointListener",
           "protect_checkpoint", "unprotect_checkpoint",
           "is_checkpoint_protected"]


# Checkpoint files that pruning must never delete. ElasticTrainer
# registers its live checkpoints here (train/fault_tolerance.py), so
# a CheckpointListener sharing a directory can never prune the file a
# rollback is about to restore.
_PROTECTED_CHECKPOINTS = set()


def protect_checkpoint(path: str) -> None:
    import os
    _PROTECTED_CHECKPOINTS.add(os.path.abspath(path))


def unprotect_checkpoint(path: str) -> None:
    import os
    _PROTECTED_CHECKPOINTS.discard(os.path.abspath(path))


def is_checkpoint_protected(path: str) -> bool:
    import os
    return os.path.abspath(path) in _PROTECTED_CHECKPOINTS


class TrainingListener:
    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_gradient_calculation(self, model):
        pass

    def iteration_done(self, model, iteration: int, score, batch_size: int):
        pass


class ScoreIterationListener(TrainingListener):
    """(optimize/listeners/ScoreIterationListener.java)."""

    def __init__(self, print_iterations: int = 10):
        self.freq = max(1, print_iterations)

    def iteration_done(self, model, iteration, score, batch_size):
        if iteration % self.freq == 0:
            logger.info("Score at iteration %d is %s", iteration,
                        float(score))


class PerformanceListener(TrainingListener):
    """samples/sec + batches/sec (PerformanceListener.java:97-119)."""

    def __init__(self, frequency: int = 1, report: bool = True):
        self.freq = max(1, frequency)
        self.report = report
        self._last_time = None
        self._samples = 0
        self._batches = 0
        self.last_samples_per_sec: Optional[float] = None
        self.last_batches_per_sec: Optional[float] = None

    def iteration_done(self, model, iteration, score, batch_size):
        self._samples += batch_size
        self._batches += 1
        if iteration % self.freq != 0:
            return
        now = time.perf_counter()
        if self._last_time is not None:
            dt = now - self._last_time
            if dt > 0:
                self.last_samples_per_sec = self._samples / dt
                self.last_batches_per_sec = self._batches / dt
                if self.report:
                    logger.info(
                        "iteration %d: %.1f samples/sec, %.2f batches/sec",
                        iteration, self.last_samples_per_sec,
                        self.last_batches_per_sec)
        self._last_time = now
        self._samples = 0
        self._batches = 0


class CollectScoresIterationListener(TrainingListener):
    """(optimize/listeners/CollectScoresIterationListener.java)."""

    def __init__(self, frequency: int = 1):
        self.freq = max(1, frequency)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, score, batch_size):
        if iteration % self.freq == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """ETA logging (optimize/listeners/TimeIterationListener.java)."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total = total_iterations
        self.freq = frequency
        self.start = time.time()

    def iteration_done(self, model, iteration, score, batch_size):
        if iteration == 0 or iteration % self.freq != 0:
            return
        elapsed = time.time() - self.start
        rate = elapsed / max(iteration, 1)
        remaining = (self.total - iteration) * rate
        logger.info("iteration %d/%d, remaining ~%.0f s", iteration,
                    self.total, remaining)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator
    (optimize/listeners/EvaluativeListener.java:34)."""

    def __init__(self, iterator, frequency: int = 100,
                 invocation: str = "iteration"):
        self.iterator = iterator
        self.freq = max(1, frequency)
        self.invocation = invocation  # 'iteration' | 'epoch'
        self.evaluations = []

    def _evaluate(self, model):
        ev = model.evaluate(self.iterator)
        self.evaluations.append(ev)
        logger.info("EvaluativeListener:\n%s", ev.stats())

    def iteration_done(self, model, iteration, score, batch_size):
        if self.invocation == "iteration" and iteration > 0 \
                and iteration % self.freq == 0:
            self._evaluate(model)

    def on_epoch_end(self, model):
        if self.invocation == "epoch":
            self._evaluate(model)


class SleepyTrainingListener(TrainingListener):
    """Debug throttle (optimize/listeners/SleepyTrainingListener.java;
    used by SharedTrainingWrapper debugLongerIterations)."""

    def __init__(self, timer_iteration_ms: float = 0.0,
                 timer_epoch_ms: float = 0.0):
        self.timer_iteration_ms = timer_iteration_ms
        self.timer_epoch_ms = timer_epoch_ms

    def iteration_done(self, model, iteration, score, batch_size):
        if self.timer_iteration_ms > 0:
            time.sleep(self.timer_iteration_ms / 1000.0)

    def on_epoch_end(self, model):
        if self.timer_epoch_ms > 0:
            time.sleep(self.timer_epoch_ms / 1000.0)


class CheckpointListener(TrainingListener):
    """Periodic model save (reference CheckpointListener semantics)."""

    def __init__(self, directory: str, save_every_n_iterations: int = 1000,
                 keep_last: int = 3):
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.freq = save_every_n_iterations
        self.keep_last = keep_last
        self._saved: List[str] = []

    def iteration_done(self, model, iteration, score, batch_size):
        if iteration == 0 or iteration % self.freq != 0:
            return
        import os
        from deeplearning4j_tpu.util.model_serializer import write_model
        path = os.path.join(self.directory, f"checkpoint_{iteration}.zip")
        write_model(model, path)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if is_checkpoint_protected(old):
                # e.g. ElasticTrainer's rollback restore target —
                # keep the file, just stop tracking it
                continue
            try:
                os.remove(old)
            except OSError:
                pass
