"""Second-order / line-search optimization algorithms.

The reference's OptimizationAlgorithm enum (nn/api/
OptimizationAlgorithm.java:26) lists STOCHASTIC_GRADIENT_DESCENT,
LINE_GRADIENT_DESCENT, CONJUGATE_GRADIENT, and LBFGS, driven by
BackTrackLineSearch (optimize/solvers/BackTrackLineSearch.java) over
the flat parameter view. First-order + schedules is the right TPU
default (the jitted train step), but the API surface exists here for
parity: full-batch optimizers over the executor's flat parameter
vector, with the loss/gradient oracle jitted once (the TPU does the
heavy lifting; the tiny s/y bookkeeping stays on host, as the
reference's solver loop does on the JVM).

Works with both executors (MultiLayerNetwork and ComputationGraph)
through params_flat/set_params_flat.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["BackTrackLineSearch", "optimize", "lbfgs", "conjugate_gradient",
           "line_gradient_descent"]


def _flat_oracle(net, ds) -> Tuple[Callable, np.ndarray]:
    """Jitted flat-vector loss/grad for a model + full batch."""
    from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
    from deeplearning4j_tpu.models.computation_graph import (
        ComputationGraph)

    from deeplearning4j_tpu.util.tree import (tree_flat_vector,
                                              tree_from_flat_vector)

    if isinstance(net, ComputationGraph):
        batch = net._batch_tuple(net._as_multi(ds))
    else:
        batch = net._batch_tuple(ds)
    template = net.params          # shapes/dtypes/order contract lives
    state = net.state              # in util/tree's flat-vector helpers

    @jax.jit
    def value_and_grad(flat):
        def loss_fn(fl):
            loss, _ = net._loss(tree_from_flat_vector(template, fl),
                                state, batch, None, training=False)
            return loss
        return jax.value_and_grad(loss_fn)(flat)

    return value_and_grad, jnp.asarray(tree_flat_vector(net.params),
                                       jnp.float32)


class BackTrackLineSearch:
    """Armijo backtracking (optimize/solvers/BackTrackLineSearch.java:
    sufficient-decrease condition with geometric step shrink)."""

    def __init__(self, c1: float = 1e-4, shrink: float = 0.5,
                 max_steps: int = 20, initial_step: float = 1.0):
        self.c1 = c1
        self.shrink = shrink
        self.max_steps = max_steps
        self.initial_step = initial_step

    def search(self, value_and_grad, x, f0, g0, direction):
        """Returns (step, x_new, f_new, g_new, ok)."""
        d_dot_g = float(jnp.vdot(direction, g0))
        if d_dot_g >= 0:       # not a descent direction
            return 0.0, x, f0, g0, False
        step = self.initial_step
        for _ in range(self.max_steps):
            x_new = x + step * direction
            f_new, g_new = value_and_grad(x_new)
            if float(f_new) <= float(f0) + self.c1 * step * d_dot_g:
                return step, x_new, f_new, g_new, True
            step *= self.shrink
        return 0.0, x, f0, g0, False


def line_gradient_descent(value_and_grad, x0, *, iterations: int = 100,
                          tol: float = 1e-8,
                          line_search: Optional[BackTrackLineSearch]
                          = None):
    """LINE_GRADIENT_DESCENT: steepest descent + line search."""
    ls = line_search or BackTrackLineSearch()
    x = x0
    f, g = value_and_grad(x)
    history = [float(f)]
    for _ in range(iterations):
        step, x, f, g, ok = ls.search(value_and_grad, x, f, g, -g)
        history.append(float(f))
        if not ok or abs(history[-2] - history[-1]) < tol:
            break
    return x, history


def conjugate_gradient(value_and_grad, x0, *, iterations: int = 100,
                       tol: float = 1e-8,
                       line_search: Optional[BackTrackLineSearch] = None):
    """CONJUGATE_GRADIENT (Polak-Ribière with automatic restart,
    optimize/solvers/ConjugateGradient.java)."""
    ls = line_search or BackTrackLineSearch()
    x = x0
    f, g = value_and_grad(x)
    d = -g
    history = [float(f)]
    for it in range(iterations):
        step, x, f_new, g_new, ok = ls.search(value_and_grad, x, f, g, d)
        history.append(float(f_new))
        if not ok or abs(float(f) - float(f_new)) < tol:
            break
        # Polak-Ribière beta; restart on non-descent / every n dims
        beta = float(jnp.vdot(g_new, g_new - g)
                     / jnp.maximum(jnp.vdot(g, g), 1e-20))
        beta = max(beta, 0.0)                      # PR+
        d = -g_new + beta * d
        if float(jnp.vdot(d, g_new)) >= 0:
            d = -g_new                             # restart
        f, g = f_new, g_new
    return x, history


def lbfgs(value_and_grad, x0, *, iterations: int = 100, history: int = 10,
          tol: float = 1e-8,
          line_search: Optional[BackTrackLineSearch] = None):
    """LBFGS (optimize/solvers/LBFGS.java): limited-memory two-loop
    recursion over (s, y) pairs + backtracking line search."""
    ls = line_search or BackTrackLineSearch()
    x = x0
    f, g = value_and_grad(x)
    S: List = []
    Y: List = []
    losses = [float(f)]
    for it in range(iterations):
        # two-loop recursion
        q = g
        alphas = []
        for s, y in zip(reversed(S), reversed(Y)):
            rho = 1.0 / float(jnp.maximum(jnp.vdot(y, s), 1e-20))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        if S:
            s, y = S[-1], Y[-1]
            gamma = float(jnp.vdot(s, y)
                          / jnp.maximum(jnp.vdot(y, y), 1e-20))
            q = gamma * q
        for (a, rho, s, y) in reversed(alphas):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        d = -q
        step, x_new, f_new, g_new, ok = ls.search(value_and_grad, x, f,
                                                  g, d)
        losses.append(float(f_new))
        if not ok:
            # fall back to steepest descent once before giving up
            step, x_new, f_new, g_new, ok = ls.search(
                value_and_grad, x, f, g, -g)
            if not ok:
                break
        S.append(x_new - x)
        Y.append(g_new - g)
        if len(S) > history:
            S.pop(0)
            Y.pop(0)
        if abs(float(f) - float(f_new)) < tol:
            x, f, g = x_new, f_new, g_new
            break
        x, f, g = x_new, f_new, g_new
    return x, losses


_ALGOS = {"lbfgs": lbfgs,
          "conjugate_gradient": conjugate_gradient,
          "line_gradient_descent": line_gradient_descent}


def optimize(net, ds, *, algorithm: str = "lbfgs",
             iterations: int = 100, **kw) -> List[float]:
    """Full-batch second-order fit of a model in place (the Solver
    facade for non-SGD OptimizationAlgorithm values). Returns the loss
    history."""
    if algorithm not in _ALGOS:
        raise ValueError(f"Unknown algorithm '{algorithm}'; "
                         f"choose from {sorted(_ALGOS)}")
    value_and_grad, x0 = _flat_oracle(net, ds)
    x, history = _ALGOS[algorithm](value_and_grad, x0,
                                   iterations=iterations, **kw)
    net.set_params_flat(np.asarray(x))
    logger.info("%s: %d evals, loss %.6f -> %.6f", algorithm,
                len(history), history[0], history[-1])
    return history
