"""Per-layer gradient normalization.

Mirrors the reference's GradientNormalization enum (applied in
BaseLayer.update via the updater chain): RenormalizeL2PerLayer,
RenormalizeL2PerParamType, ClipElementWiseAbsoluteValue,
ClipL2PerLayer, ClipL2PerParamType. Applied to the raw gradients
inside the jitted train step, before the optax update — matching where
the reference applies it (pre-updater).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["normalize_layer_gradients"]

_EPS = 1e-8


def _global_norm(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + _EPS)


def normalize_layer_gradients(grads, kind: str, threshold: float):
    """grads: one layer's param dict. Returns transformed dict."""
    k = (kind or "").lower()
    if not k or k == "none":
        return grads
    if k == "renormalize_l2_per_layer":
        n = _global_norm(grads)
        return jax.tree_util.tree_map(lambda g: g / n, grads)
    if k == "renormalize_l2_per_param_type":
        return {key: g / (jnp.sqrt(jnp.sum(g * g)) + _EPS)
                for key, g in grads.items()}
    if k == "clip_element_wise_absolute_value":
        t = threshold
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -t, t), grads)
    if k == "clip_l2_per_layer":
        n = _global_norm(grads)
        scale = jnp.minimum(1.0, threshold / n)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if k == "clip_l2_per_param_type":
        out = {}
        for key, g in grads.items():
            n = jnp.sqrt(jnp.sum(g * g)) + _EPS
            out[key] = g * jnp.minimum(1.0, threshold / n)
        return out
    raise ValueError(f"Unknown gradient normalization '{kind}'")


def apply_gradient_normalization(layers, grads):
    """Apply each layer's configured normalization to its grad subtree.
    ``layers``: layer configs (list or dict of name->config);
    ``grads``: matching pytree of per-layer param dicts."""
    if isinstance(grads, dict) and not isinstance(layers, list):
        out = {}
        for name, g in grads.items():
            cfg = layers[name]
            kind = getattr(cfg, "gradient_normalization", None)
            if kind:
                g = normalize_layer_gradients(
                    g, kind,
                    getattr(cfg, "gradient_normalization_threshold", 1.0))
            out[name] = g
        return out
    out = []
    for cfg, g in zip(layers, grads):
        kind = getattr(cfg, "gradient_normalization", None)
        if kind:
            g = normalize_layer_gradients(
                g, kind,
                getattr(cfg, "gradient_normalization_threshold", 1.0))
        out.append(g)
    return out
