"""Elastic / fault-tolerant training.

The reference's failure handling is minimal by design (SURVEY §5:
InvalidScoreIterationTerminationCondition + Spark task retry; no
elastic membership). On TPU pods the real-world failure modes are
preemption (SIGTERM with a grace window) and numeric blow-ups; the
idiomatic recovery is checkpoint-based restart. :class:`ElasticTrainer`
packages that loop:

- periodic ATOMIC checkpoints (tmp + rename; a preemption mid-write
  never corrupts the latest checkpoint), pruned to ``keep`` newest;
- the DATA POSITION (epoch index, batch index) rides inside the
  checkpoint zip, so a resumed or rolled-back run fast-forwards the
  iterator to exactly where the checkpointed model stopped —
  kill-at-iteration-k + resume reproduces the uninterrupted run
  bit-for-bit for a deterministic iterator (the reference's
  serialization-regression discipline, SURVEY §4.3, applied here);
- automatic resume from the newest valid checkpoint on construction;
- SIGTERM → checkpoint immediately and stop cleanly (the TPU
  preemption grace-window contract); the handler is only installed on
  the main thread (signal.signal raises elsewhere);
- non-finite loss → roll back to the last checkpoint (model AND data
  position), REPLAY the batches in between, and skip exactly the one
  batch that produced the non-finite loss (InvalidScore-skip semantics:
  a deterministic poison batch must not re-diverge the replay forever).
  Bounded by ``max_rollbacks`` per incident: the rollback counter
  decays to zero after ``heal_after`` consecutive healthy iterations,
  so the bound is per-divergence, not per-lifetime. The SKIP SET is
  persisted: a rollback immediately re-checkpoints (restored params +
  skip), so a process killed right after a rollback resumes
  skip-aware — restart == uninterrupted holds THROUGH rollbacks, not
  just for clean kills;
- the replay fast-forward assumes a DETERMINISTIC same-order
  iterator; that contract is CHECKED, not just documented: each
  checkpoint carries a rolling fingerprint chain over every batch
  consumed this epoch, and a resumed run recomputes the chain over
  the replayed batches — any reorder, substitution, or shortfall in
  ANY replayed ordinal fails loudly instead of silently diverging;
- checkpoint DURABILITY (the chaos PR): every restore first passes
  :func:`~deeplearning4j_tpu.util.model_serializer.verify_checkpoint`
  (zip CRCs + the CRC32 manifest written into every zip); a
  truncated/corrupted generation is QUARANTINED (renamed
  ``*.corrupt``, counted as ``checkpoint_quarantined_total``) and the
  trainer falls back generation by generation to the newest intact
  checkpoint instead of dying on ``BadZipFile``. A failed checkpoint
  WRITE (ENOSPC, quota) is a missed checkpoint, not a dead run: the
  partial tmp is removed, ``checkpoint_write_failures_total`` counts
  it, and training continues on the previous generation. Stale
  ``*.tmp<pid>`` files leaked by a crash mid-write are swept on
  trainer start. The ``train.step`` chaos site fires right before
  each step (crash / hang / nan-poison drills).

- ASYNC CHECKPOINTING (the preemption PR): with
  ``async_checkpoint=True`` a save costs the train thread only a
  device→host snapshot (``snapshot_model``) — serialization, zip,
  CRC manifest and the atomic rename run on a single background
  writer thread (one in-flight write; a newer save supersedes any
  queued one). ``fit()`` exit, the SIGTERM grace path, and rollback
  BARRIER on the writer, so durability guarantees are unchanged;
  ``checkpoint_write_seconds{phase="blocked"|"total"}`` splits what
  the train thread paid from what the write cost.
- CHECKPOINTABLE ITERATOR STATE: iterators implementing the opt-in
  ``state_dict()/load_state_dict()`` protocol (see
  ``data/iterators.DataSetIterator``) resume by direct state restore
  — no per-batch replay, and no deterministic-iterator requirement;
  the fingerprint-replay fast-forward remains the fallback for
  stateless iterators, and a replay that runs DRY now raises the
  distinct "iterator shorter than checkpointed position" error
  instead of blaming determinism.

Works with both executors via the zip serializer.
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import os
import re
import signal
import sys
import threading
import time
import zipfile
from typing import Optional

import numpy as np

from deeplearning4j_tpu import chaos

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ElasticTrainer", "CheckpointWriter"]

_CKPT_RE = re.compile(r"ckpt_(\d+)\.zip$")
_TMP_RE = re.compile(r"ckpt_\d+\.zip\.tmp(\d+)$")
_POS_ENTRY = "data_position.json"
_ITSTATE_ENTRY = "iterator_state.json"

# tmp files an async writer in THIS process is writing right now:
# the stale-tmp sweep must not treat a live same-pid write as a leak
# (a second trainer constructed in-process — the restart-in-process
# pattern — would otherwise delete it mid-write)
_LIVE_TMPS: set = set()
_LIVE_TMPS_LOCK = threading.Lock()


class _CheckpointWriter:
    """Single background checkpoint writer: at most ONE write in
    flight, with a depth-1 coalescing queue — a save submitted while
    a write is in flight SUPERSEDES any save still queued (the newest
    state is the only one worth persisting; an old queued snapshot is
    strictly stale). ``barrier()`` waits until both the in-flight and
    the queued write have drained and re-raises anything a write
    raised — the fit-exit / SIGTERM-grace / rollback sync point that
    turns "submitted" into "durable"."""

    def __init__(self):
        self._cond = threading.Condition()
        self._pending = None          # the (single) queued job
        self._busy = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self.superseded = 0           # queued saves dropped by newer
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True)
        self._thread.start()

    def submit(self, job) -> bool:
        """Queue ``job`` (a thunk); returns True when it replaced an
        older queued job. Raises any error a PREVIOUS write left
        behind, so a dying disk surfaces at the next save, not only
        at fit exit."""
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            if self._closed:
                raise RuntimeError("checkpoint writer is closed")
            replaced = self._pending is not None
            if replaced:
                self.superseded += 1
            self._pending = job
            self._cond.notify_all()
        return replaced

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    # heartbeat, not an unbounded block (GL008): the
                    # predicate loop re-checks closed/pending either
                    # way, and the writer thread stays interruptible
                    self._cond.wait(1.0)
                if self._pending is None:
                    return                      # closed and drained
                job, self._pending = self._pending, None
                self._busy = True
            try:
                job()
            except BaseException as e:          # surfaced at barrier
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def idle(self) -> bool:
        with self._cond:
            return not self._busy and self._pending is None

    def barrier(self, timeout: Optional[float] = None) -> None:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._busy or self._pending is not None:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "checkpoint writer still busy after "
                        f"{timeout}s")
                self._cond.wait(remaining)
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def close(self, timeout: Optional[float] = None) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._cond:
            if self._error is not None:
                err, self._error = self._error, None
                raise err


# public name for the async-checkpoint writer: the parameter server
# (parallel/paramserver.py) reuses the same one-in-flight coalescing
# writer + barrier discipline for its durable version snapshots, so
# "PS failover restores the last durable version" rides exactly the
# machinery the preemption PR proved out
CheckpointWriter = _CheckpointWriter


def _hash_array(h, a) -> None:
    a = np.asarray(a)
    flat = a.reshape(-1) if a.flags.c_contiguous else a.ravel()
    k = 256
    n = flat.size
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    for window in (flat[:k], flat[n // 2:n // 2 + k],
                   flat[max(0, n - k):]):
        h.update(np.ascontiguousarray(window).tobytes())


def _fingerprint(ds) -> str:
    """Cheap content fingerprint of a batch: shape + dtype + three
    sampled 1KB windows (head / middle / tail) of EVERY feature AND
    label array (all of them for a MultiDataSet). Labels are folded
    in deliberately: a replayed iterator that kept features but
    substituted or reordered labels would otherwise pass the
    determinism check and silently train on wrong targets. Sampling
    windows (not just the head) catches shared-BOS/padding layouts
    whose leading bytes are identical across batches; slicing views
    before ``tobytes`` keeps the copy small regardless of batch
    size."""
    h = hashlib.sha1()
    for group in (ds.features, getattr(ds, "labels", None)):
        if group is None:
            continue
        if not isinstance(group, (list, tuple)):
            group = (group,)
        h.update(b"|g%d" % len(group))
        for slot, a in enumerate(group):
            # per-slot marker even for None: [x, None, y] must not
            # fingerprint equal to [x, y, None]
            h.update(b"|s%d" % slot)
            if a is None:
                h.update(b"<none>")
            else:
                _hash_array(h, a)
    return h.hexdigest()


def _chain(prev: str, fp: str) -> str:
    """Rolling digest over consumed batches: order-sensitive, so a
    replay that reorders ANY prefix batch (not just the last one)
    mismatches."""
    return hashlib.sha1((prev + fp).encode()).hexdigest()


class ElasticTrainer:
    def __init__(self, model, checkpoint_dir: str, *,
                 save_every: int = 100, keep: int = 3,
                 max_rollbacks: int = 5, heal_after: Optional[int] = None,
                 handle_sigterm: bool = True, wrapper=None,
                 lr_drop_on_rollback: Optional[float] = None,
                 async_checkpoint: bool = False,
                 steps_per_device_call: int = 1,
                 mesh_spec=None):
        # async_checkpoint: take checkpoints OFF the train thread —
        # save_checkpoint snapshots params/opt-state device→host at
        # the step boundary (cheap) and hands serialization + zip +
        # manifest + atomic rename to a single background writer
        # (one in-flight write; a newer save supersedes a queued
        # one). fit() exit, the SIGTERM grace path, and rollback all
        # barrier on the writer, so "returned from fit" still means
        # "durable". checkpoint_write_seconds{phase=blocked|total}
        # makes the win measurable.
        # lr_drop_on_rollback: multiply the configured learning rate
        # by this factor (< 1) on every rollback — the standard
        # "restart from the last good checkpoint with a cooler LR"
        # move for repeated divergence. Rebuilding the optimizer
        # resets its state (momentum), which is exactly the restart
        # semantics wanted after a blow-up.
        # wrapper: optional ParallelWrapper around ``model`` — batches
        # then train data-parallel while checkpoint/restore still talks
        # to the underlying model (ParallelWrapper.java analog: the
        # wrapper composes with, not replaces, the model's lifecycle)
        # steps_per_device_call: k-step fused training (the
        # dispatch-bound fix, models/kstep.py) — the trainer collects
        # k batches per window (fingerprint / skip-set / chaos still
        # run PER LOGICAL STEP at collection time), dispatches them
        # as one fused device program via ``model.fit_batches``, and
        # checkpoints only at window boundaries so the iterator
        # cursor always lands on a k-step boundary — preemption
        # resume stays bit-identical. Non-finite/rollback detection
        # lag is bounded by k (every step's loss still comes back).
        # NOTE on listener semantics: the k>1 path drives
        # ``model.fit_batches`` (no epoch hooks, ``epoch_count``
        # untouched), while the legacy k=1 path calls
        # ``model.fit(ds)`` per batch, which fires
        # on_epoch_start/on_epoch_end and bumps ``epoch_count`` once
        # PER BATCH — a historical quirk kept for checkpoint/test
        # compatibility. Params are unaffected either way; listeners
        # keying off epoch hooks see the (saner) windowed cadence
        # under k>1.
        # mesh_spec: train SHARDED over a declarative device mesh
        # ("dp=4,tp=2" | dict | JSON — parallel/mesh_spec.py): the
        # spec is installed on the model up front (so a checkpoint
        # restore re-places onto the mesh too) and composes with
        # steps_per_device_call — k sharded steps fused into one
        # device program per window. Mutually exclusive with
        # ``wrapper`` (two ways to state the same parallelism).
        self.model = model
        self.wrapper = wrapper
        self.k = int(steps_per_device_call)
        if self.k < 1:
            # same contract as the executors' fit(): an invalid k
            # fails loudly everywhere instead of silently clamping
            # in one mode and crashing in another
            raise ValueError("steps_per_device_call must be >= 1")
        if mesh_spec is not None:
            if wrapper is not None:
                raise ValueError(
                    "pass either mesh_spec (the executor's sharded "
                    "fit path) or wrapper (an explicit "
                    "ParallelWrapper), not both")
            model.use_mesh(mesh_spec)
        if wrapper is not None and self.k > 1 and not (
                getattr(wrapper, "supports_fused_windows",
                        lambda: False)()):
            # seq / compressed meshes have no fused k-step program —
            # failing loudly beats silently training with a
            # different cadence than the operator asked for. Pure-dp
            # and dp x tp wrappers DO fuse (wrapper.fit_batches runs
            # the window as one sharded device program).
            raise ValueError(
                "steps_per_device_call > 1 needs a wrapper mesh "
                "that fuses (data / data x model, no "
                "dcn_compression); this wrapper's mesh step is "
                "per-batch — drop the wrapper or use "
                "steps_per_device_call=1")
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.save_every = max(1, save_every)
        self.keep = max(1, keep)
        self.max_rollbacks = max_rollbacks
        self.heal_after = (save_every if heal_after is None
                           else max(1, heal_after))
        self.handle_sigterm = handle_sigterm
        self.lr_drop_on_rollback = lr_drop_on_rollback
        self.async_checkpoint = async_checkpoint
        self._writer_obj: Optional[_CheckpointWriter] = None
        self._active_iterator = None   # the fit() iterator, for state
        self._it_state: Optional[dict] = None  # restored, pending apply
        self.rollbacks = 0           # current incident (decays)
        self.total_rollbacks = 0     # lifetime (never decays)
        self._healthy_streak = 0
        self._stop_requested = False
        self._epoch = 0          # data position: epoch index
        self._batch = 0          # batches consumed within that epoch
        self._skip = set()       # (epoch, batch) ordinals to skip
        self._fp_chain = ""      # rolling digest of every batch
        #                          consumed this epoch (determinism
        #                          check on replay)
        self._sweep_stale_tmp()
        self._resume()

    # -- checkpoint plumbing ----------------------------------------------
    def _ckpts(self):
        out = []
        for f in os.listdir(self.dir):
            m = _CKPT_RE.match(f)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, f)))
        return sorted(out)

    def latest_checkpoint(self) -> Optional[str]:
        cks = self._ckpts()
        return cks[-1][1] if cks else None

    def _sweep_stale_tmp(self) -> None:
        """A crash mid-``write_model`` leaks ``ckpt_N.zip.tmp<pid>``
        forever (the pid suffix means a restarted process never
        collides with, and so never cleans, the old name); sweep them
        on start — but only when the owning pid is dead, so a second
        trainer pointed at a shared directory can never delete a
        write another live process is mid-way through."""
        for f in os.listdir(self.dir):
            m = _TMP_RE.match(f)
            if not m:
                continue
            pid = int(m.group(1))
            path = os.path.join(self.dir, f)
            if pid != os.getpid():
                try:
                    os.kill(pid, 0)      # probe: is the owner alive?
                    continue             # yes — not ours to sweep
                except ProcessLookupError:
                    pass                 # dead owner: stale for sure
                except OSError:
                    continue             # EPERM etc.: assume alive
            else:
                with _LIVE_TMPS_LOCK:
                    if path in _LIVE_TMPS:
                        continue         # another trainer's writer is
                #                          mid-write IN THIS process
            try:
                os.remove(path)
                logger.info("swept stale checkpoint tmp %s", path)
            except OSError:
                pass

    def save_checkpoint(self):
        """Snapshot + persist the current generation. Sync mode
        returns the final path; async mode snapshots device→host,
        hands the write to the background writer and returns None
        (the path is knowable only after the rename — barrier via
        :meth:`checkpoint_barrier` when durability matters NOW).
        ``checkpoint_write_seconds{phase="blocked"}`` records what
        this call cost the train thread either way."""
        from deeplearning4j_tpu.util.model_serializer import (
            snapshot_model)
        t0 = time.perf_counter()
        it = self.model.iteration_count
        # the data position rides in the same zip: one atomic artifact,
        # no model/position skew after a mid-write preemption; passing
        # it through the writer (not appending after) puts it under
        # the integrity manifest's CRC too
        pos = json.dumps(
            {"epoch": self._epoch, "batch": self._batch,
             # the poison-skip set rides in the checkpoint: a
             # restart after a rollback must not pay a second
             # rollback to rediscover a deterministic poison batch
             "skip": sorted(list(p) for p in self._skip),
             "fp_chain": self._fp_chain})
        extra = {_POS_ENTRY: pos}
        it_state = self._iterator_state()
        if it_state is not None:
            extra[_ITSTATE_ENTRY] = json.dumps(it_state)
        snap = snapshot_model(self.model)
        if self.async_checkpoint:
            # epoch/batch bound NOW: the writer runs later, when the
            # train thread has moved on
            self._writer().submit(
                lambda e=self._epoch, b=self._batch:
                self._write_generation(snap, extra, it, e, b))
            self._observe_write("blocked",
                                time.perf_counter() - t0)
            return None
        path = self._write_generation(snap, extra, it, self._epoch,
                                      self._batch)
        self._observe_write("blocked", time.perf_counter() - t0)
        return path

    def _iterator_state(self) -> Optional[dict]:
        """The active iterator's checkpointable state — persisted
        only when its cursor agrees with the trainer's batch ordinal
        (right after a rollback the iterator still sits at the crash
        position while the trainer has been restored; persisting that
        skew would corrupt a later resume — omit it and let that one
        generation fall back to replay)."""
        # a rollback re-checkpoints BEFORE the fit loop repositions
        # the iterator: the state restored from the rolled-back-to
        # zip (pending in _it_state) is the truthful position then —
        # persisting it keeps even that generation state-resumable
        if (self._it_state is not None
                and int(self._it_state.get("cursor", -1))
                == self._batch):
            return self._it_state
        src = self._active_iterator
        sd = getattr(src, "state_dict", None)
        if not callable(sd):
            return None
        try:
            st = sd()
        except Exception:
            logger.exception("iterator state_dict() failed; "
                             "checkpoint will resume via replay")
            return None
        if st is None or int(st.get("cursor", -1)) != self._batch:
            return None
        return st

    def _write_generation(self, snap, extra, it, epoch, batch):
        """Serialize + zip + manifest + atomic rename + prune: the
        shared tail of sync and async saves (async runs it on the
        writer thread). ``checkpoint_write_seconds{phase="total"}``
        records the full cost wherever it runs."""
        final = os.path.join(self.dir, f"ckpt_{it}.zip")
        tmp = final + f".tmp{os.getpid()}"
        t0 = time.perf_counter()
        from deeplearning4j_tpu.util.model_serializer import (
            write_snapshot)
        with _LIVE_TMPS_LOCK:
            _LIVE_TMPS.add(tmp)
        try:
            try:
                write_snapshot(snap, tmp, extra_entries=extra)
                os.replace(tmp, final)      # atomic on POSIX
            finally:
                with _LIVE_TMPS_LOCK:
                    _LIVE_TMPS.discard(tmp)
        except OSError as e:
            # ENOSPC / quota / dying disk mid-write: a missed
            # checkpoint must not kill the run — clean the partial
            # tmp, count it, and keep training on the previous
            # generation
            try:
                os.remove(tmp)
            except OSError:
                pass
            self._count("checkpoint_write_failures_total",
                        "checkpoint writes that failed (ENOSPC, ...)")
            logger.warning("checkpoint write at iteration %d failed "
                           "(%r); continuing on the previous "
                           "generation", it, e)
            return None
        self._observe_write("total", time.perf_counter() - t0)
        # mark live trainer checkpoints protected so a co-attached
        # CheckpointListener's keep_last pruning can never delete the
        # file a rollback is about to restore
        from deeplearning4j_tpu.train import listeners as _listeners
        _listeners.protect_checkpoint(final)
        # pruning runs on whichever thread wrote the generation (the
        # writer thread in async mode — the only thread touching
        # checkpoint files there, so keep-pruning can never race an
        # in-flight tmp); _CKPT_RE matches finals only, never tmps
        for _, path in self._ckpts()[:-self.keep]:
            try:
                os.remove(path)
            except OSError:
                pass
            _listeners.unprotect_checkpoint(path)
        logger.info("checkpoint at iteration %d (epoch %d, batch %d) "
                    "-> %s", it, epoch, batch, final)
        return final

    def _writer(self) -> _CheckpointWriter:
        if self._writer_obj is None:
            self._writer_obj = _CheckpointWriter()
        return self._writer_obj

    def checkpoint_barrier(self,
                           timeout: Optional[float] = None) -> None:
        """Wait until no checkpoint write is queued or in flight;
        re-raises writer errors. No-op in sync mode."""
        if self._writer_obj is not None:
            self._writer_obj.barrier(timeout)

    def close(self) -> None:
        """Drain and stop the background writer (if any)."""
        if self._writer_obj is not None:
            w, self._writer_obj = self._writer_obj, None
            w.close()

    @staticmethod
    def _observe_write(phase: str, seconds: float) -> None:
        try:
            from deeplearning4j_tpu.observability.registry import (
                REGISTRY)
            REGISTRY.histogram(
                "checkpoint_write_seconds",
                help="checkpoint write time: phase=blocked is what "
                     "the train thread paid (snapshot + handoff in "
                     "async mode; the whole write in sync mode), "
                     "phase=total the full serialize+zip+rename",
                labels={"phase": phase}).record(seconds)
        except Exception:
            pass

    @staticmethod
    def _count(name: str, help: str) -> None:
        from deeplearning4j_tpu.observability.registry import safe_inc
        safe_inc(name, help=help)

    def _restore_into_model(self, path: str):
        from deeplearning4j_tpu.util.model_serializer import (
            restore_model, verify_checkpoint)
        verify_checkpoint(path)    # CRC gate BEFORE trusting the zip
        loaded = restore_model(path)
        m = self.model
        m.params = loaded.params
        m.state = loaded.state
        m.opt_state = loaded.opt_state
        m.iteration_count = loaded.iteration_count
        m.epoch_count = loaded.epoch_count
        # a mesh-sharded model restores HOST arrays — re-place them
        # per the installed context, or the next (output-pinned)
        # step would see default-device inputs and die on a device
        # mismatch instead of resuming
        ctx = getattr(m, "_mesh_ctx", None)
        if ctx is not None:
            ctx.place_model(m)
        self._it_state = None
        try:
            with zipfile.ZipFile(path, "r") as z:
                pos = json.loads(z.read(_POS_ENTRY))
                if _ITSTATE_ENTRY in z.namelist():
                    self._it_state = json.loads(z.read(_ITSTATE_ENTRY))
            self._epoch = int(pos["epoch"])
            self._batch = int(pos["batch"])
            # MERGE the persisted skip set (a rollback restores an
            # older checkpoint whose zip may predate the newest skip
            # entry — skips are monotone within an incident)
            self._skip |= {tuple(p) for p in pos.get("skip", [])}
            self._fp_chain = pos.get("fp_chain") or ""
        except (KeyError, json.JSONDecodeError):
            # pre-position checkpoint (older format): restart the epoch
            self._epoch, self._batch = 0, 0
            self._it_state = None

    def _quarantine(self, path: str, err: BaseException) -> None:
        """Rename a checkpoint that failed verification/restore to
        ``*.corrupt`` — out of the generation sequence (so fallback
        terminates) but kept on disk as evidence."""
        from deeplearning4j_tpu.train import listeners as _listeners
        q = path + ".corrupt"
        logger.warning("checkpoint %s failed integrity/restore (%r): "
                       "quarantining as %s and falling back to the "
                       "previous generation", path, err, q)
        try:
            os.replace(path, q)
        except FileNotFoundError:
            return              # already gone — nothing to quarantine
        except OSError:
            # last resort: a file we can neither rename nor remove
            # would make the fallback loop spin forever
            try:
                os.remove(path)
            except FileNotFoundError:
                return
        _listeners.unprotect_checkpoint(path)
        self._count("checkpoint_quarantined_total",
                    "corrupt/truncated checkpoints quarantined on "
                    "restore")

    def _restore_latest_intact(self) -> Optional[str]:
        """Restore the newest checkpoint that passes verification,
        quarantining corrupt generations on the way down; None when
        no intact generation remains."""
        from deeplearning4j_tpu.chaos.retry import DEFAULT_IO_RETRY
        from deeplearning4j_tpu.util.model_serializer import (
            CheckpointIntegrityError)
        while True:
            path = self.latest_checkpoint()
            if path is None:
                return None
            try:
                # transient read errors (NFS blip, injected IOError)
                # get the shared retry policy FIRST — a healthy file
                # must not be quarantined for a flaky read
                DEFAULT_IO_RETRY.call(self._restore_into_model, path)
                return path
            except (CheckpointIntegrityError, zipfile.BadZipFile,
                    OSError, KeyError, ValueError) as e:
                # BadZipFile/OSError/ValueError: rot the CRC gate
                # could not see (or chaos injected mid-read);
                # KeyError: arrays missing vs this model's config
                self._quarantine(path, e)

    def _resume(self):
        if not self._ckpts():
            return
        if self.model.params is None:
            self.model.init()
        path = self._restore_latest_intact()
        if path is None:
            logger.warning("no intact checkpoint in %s; starting "
                           "fresh", self.dir)
            return
        logger.info("resumed from %s (iteration %d, epoch %d, "
                    "batch %d)", path, self.model.iteration_count,
                    self._epoch, self._batch)

    # -- the loop -----------------------------------------------------------
    def fit(self, iterator, *, epochs: int = 1,
            until_epoch: Optional[int] = None) -> "ElasticTrainer":
        """``epochs`` is RELATIVE (train N more epochs from wherever
        the trainer is — a resumed trainer continues); ``until_epoch``
        is an ABSOLUTE target epoch index: rerunning the same
        ``fit(until_epoch=N)`` command after a kill produces exactly
        the uninterrupted run (restart == uninterrupted)."""
        target = (self._epoch + max(0, epochs)
                  if until_epoch is None else until_epoch)
        model = self.model
        if model.params is None:
            model.init()
        prev_handler = None
        if (self.handle_sigterm
                and threading.current_thread() is threading.main_thread()):
            def on_term(signum, frame):
                # preemption grace window: persist, then stop cleanly
                self._stop_requested = True
            prev_handler = signal.signal(signal.SIGTERM, on_term)
        elif self.handle_sigterm:
            logger.info("fit() on a non-main thread: SIGTERM handler "
                        "not installed (signal.signal would raise)")
        try:
            self._active_iterator = iterator
            if self.latest_checkpoint() is None:
                self.save_checkpoint()       # iteration-0 restart point
            while self._epoch < target and not self._stop_requested:
                # STATEFUL RESUME: an iterator implementing the
                # state_dict/load_state_dict protocol is repositioned
                # directly to the checkpointed cursor — O(1)-ish, no
                # batch replay, and no deterministic-iterator
                # requirement (the state pins the epoch's rng). The
                # fingerprint-replay fast-forward below remains the
                # fallback for stateless iterators.
                state_resumed = False
                if (self._batch and self._it_state is not None
                        and hasattr(iterator, "load_state_dict")):
                    try:
                        iterator.load_state_dict(self._it_state)
                        state_resumed = True
                        logger.info(
                            "iterator state restored (epoch %d, "
                            "cursor %d): resuming without replay",
                            self._epoch, self._batch)
                    except NotImplementedError:
                        pass
                elif hasattr(iterator, "load_state_dict"):
                    # PIN the iterator's epoch to the trainer's own
                    # counter: the shuffle permutation becomes a pure
                    # function of (seed, trainer epoch), identical in
                    # an uninterrupted run and in any restart — a
                    # fresh process's iterator would otherwise count
                    # resets from zero and replay old permutations
                    # (epoch-boundary restarts, replay after a
                    # rollback-skewed save)
                    try:
                        iterator.load_state_dict(
                            {"cursor": 0, "epoch": self._epoch + 1})
                    except NotImplementedError:
                        pass
                self._it_state = None
                if hasattr(iterator, "reset"):
                    iterator.reset()
                it = iter(iterator)
                # fast-forward a resumed/rolled-back run to the
                # checkpointed batch — restart == uninterrupted for a
                # deterministic iterator; the rolling fingerprint
                # chain CHECKS that contract over EVERY replayed
                # ordinal (any reorder or shortfall mismatches)
                fwd_chain = ""
                replayed = 0
                for k in range(0 if state_resumed else self._batch):
                    ds = next(it, None)
                    if ds is None:
                        fwd_chain = None
                        break
                    replayed = k + 1
                    fwd_chain = _chain(fwd_chain, _fingerprint(ds))
                if fwd_chain is None:
                    # a shortfall is ITS OWN failure mode — the
                    # iterator ran dry before reaching the
                    # checkpointed position (dataset shrank, wrong
                    # file, truncated shard); calling that
                    # "non-deterministic" sends the operator
                    # debugging shuffle seeds instead of the data
                    raise RuntimeError(
                        f"iterator shorter than checkpointed "
                        f"position: the resume fast-forward for "
                        f"epoch {self._epoch} needed {self._batch} "
                        f"batches but the iterator yielded only "
                        f"{replayed} — the data source shrank (or "
                        f"the wrong one was passed) since the "
                        f"checkpoint was written")
                if (not state_resumed and self._batch
                        and self._fp_chain
                        and fwd_chain != self._fp_chain):
                    raise RuntimeError(
                        f"iterator is not deterministic: the "
                        f"{self._batch} batches replayed for epoch "
                        f"{self._epoch} differ from the ones consumed "
                        f"before the restart — the replay "
                        f"fast-forward requires a same-order iterator "
                        f"(disable shuffling or seed it per-epoch)")
                if self.k > 1:
                    rolled_back = self._run_epoch_kstep(it)
                    if rolled_back or self._stop_requested:
                        continue
                    self._epoch += 1
                    self._batch = 0
                    self._fp_chain = ""
                    continue
                rolled_back = False
                while True:
                    # check BEFORE pulling: a batch fetched after the
                    # stop request would never train, but it would
                    # advance a stateful iterator's cursor past the
                    # trainer's position and cost the grace
                    # checkpoint its iterator state
                    if self._stop_requested:
                        break
                    ds = next(it, None)
                    if ds is None:
                        break
                    self._fp_chain = _chain(self._fp_chain,
                                            _fingerprint(ds))
                    if (self._epoch, self._batch) in self._skip:
                        self._batch += 1     # the poisoned batch
                        continue
                    # chaos site: crash raises (a simulated
                    # preemption — resume must reproduce the
                    # uninterrupted run), hang sleeps, nan poisons
                    # this one batch (exercising the rollback path)
                    ds = self._chaos_step(ds)
                    try:
                        if self.wrapper is not None:
                            # fit_batch, not fit([ds]): the trainer
                            # owns the epoch loop — the wrapper must
                            # not bump epoch_count or fire epoch
                            # hooks per batch (and must not spin a
                            # prefetch thread per single-batch list)
                            self.wrapper.fit_batch(ds)
                        else:
                            model.fit(ds)
                    except Exception as e:
                        # HealthMonitor's rollback policy raises a
                        # rollback-flagged TrainingDivergedError from
                        # the listener chain: restore the last good
                        # checkpoint and continue, same as a
                        # non-finite loss. Anything else propagates.
                        if not getattr(e, "rollback", False):
                            raise
                        self._batch += 1     # batch was consumed
                        logger.warning(
                            "health monitor requested rollback: %s", e)
                        self._rollback()
                        rolled_back = True
                        break
                    self._batch += 1
                    loss = float(model.score_value)
                    if not np.isfinite(loss):
                        self._rollback()
                        rolled_back = True
                        break            # re-enter at restored position
                    self._healthy_streak += 1
                    if (self.rollbacks
                            and self._healthy_streak >= self.heal_after):
                        self.rollbacks = 0   # incident over
                    if model.iteration_count % self.save_every == 0:
                        self.save_checkpoint()
                if rolled_back or self._stop_requested:
                    continue
                self._epoch += 1
                self._batch = 0
                self._fp_chain = ""
            if self._stop_requested:
                # the preemption grace protocol: the snapshot is
                # taken HERE (immediately), the persist rides the
                # background writer (async mode), and the barrier in
                # the finally below guarantees durability before fit
                # returns — signal → snapshot → persist → clean stop
                self.save_checkpoint()
                logger.warning("stop requested (preemption?): "
                               "checkpointed at iteration %d",
                               model.iteration_count)
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
            self._active_iterator = None
            # returning from fit() means every submitted checkpoint
            # is durable (and surfaces any write error — a crash
            # injected into the writer thread re-raises here, dying
            # exactly as the preempted process would); when fit is
            # ALREADY unwinding an exception, the writer error must
            # not mask it
            if sys.exc_info()[0] is None:
                self.checkpoint_barrier()
            else:
                try:
                    self.checkpoint_barrier()
                except BaseException:
                    logger.exception("checkpoint writer failed "
                                     "during fit-exception unwind")
        return self

    def _run_epoch_kstep(self, it) -> bool:
        """Window-at-a-time epoch body for ``steps_per_device_call=k``:
        collect up to k batches (fingerprint chain, skip set and the
        ``train.step`` chaos site all run PER LOGICAL STEP, exactly as
        in the per-step loop), dispatch them as ONE fused device call
        via ``model.fit_batches``, then inspect every step's loss.
        Checkpoints happen only between windows — the iterator cursor
        always agrees with ``self._batch`` there. A SIGTERM closes
        the window under collection early (the partial window trains
        through the pre-compiled k=1 program), so the grace
        checkpoint lands within about one step of the signal, same as
        the per-step loop. Returns True when a rollback was taken
        (the caller restarts the epoch from the restored
        position)."""
        model = self.model
        k = self.k
        while True:
            if self._stop_requested:
                return False
            window = []                      # [(ordinal, ds)]
            exhausted = False
            while len(window) < k:
                # honor a SIGTERM mid-collection: close the window
                # early (a partial window trains through the k=1
                # program) so the grace checkpoint lands within ~one
                # step, like the per-step loop — the cursor still
                # equals the trained count and fused vs single-step
                # are bit-identical, so resume is unaffected
                if self._stop_requested:
                    break
                ds = next(it, None)
                if ds is None:
                    exhausted = True
                    break
                self._fp_chain = _chain(self._fp_chain,
                                        _fingerprint(ds))
                ordinal = self._batch
                self._batch += 1
                if (self._epoch, ordinal) in self._skip:
                    continue                 # the poisoned batch
                ds = self._chaos_step(ds)
                window.append((ordinal, ds))
            if window:
                it_before = model.iteration_count
                try:
                    # full windows fuse into one scan program; the
                    # epoch tail (len < k) runs through the
                    # pre-compiled k=1 program — no mid-epoch trace.
                    # With a wrapper the SAME window machinery runs
                    # on its mesh (wrapper.fit_batches: window
                    # fusion + mesh step in one sharded program)
                    fit_batches = (self.wrapper.fit_batches
                                   if self.wrapper is not None
                                   else model.fit_batches)
                    losses = fit_batches(
                        [d for _, d in window],
                        steps_per_device_call=k)
                except Exception as e:
                    if not getattr(e, "rollback", False):
                        raise
                    # HealthMonitor raised from the listener pass at
                    # some sub-step: the executor stamps the live
                    # window entry on _window_batch_index (NOT
                    # derivable from iteration deltas — a tBPTT entry
                    # advances the iteration counter once per chunk)
                    try:
                        idx = int(getattr(model, "_window_batch_index",
                                          0))
                    except (TypeError, ValueError):
                        idx = 0
                    idx = min(max(idx, 0), len(window) - 1)
                    logger.warning(
                        "health monitor requested rollback: %s", e)
                    self._rollback(
                        skip_ordinal=(self._epoch, window[idx][0]))
                    return True
                bad = np.flatnonzero(~np.isfinite(
                    np.asarray(losses, dtype=np.float64)))
                if bad.size:
                    # first non-finite step in the window: skip THAT
                    # ordinal on replay (later window steps trained on
                    # garbage params, but the rollback recomputes them
                    # from the restored checkpoint — same trajectory
                    # the per-step loop produces)
                    self._rollback(skip_ordinal=(
                        self._epoch, window[int(bad[0])][0]))
                    return True
                self._healthy_streak += len(window)
                if (self.rollbacks
                        and self._healthy_streak >= self.heal_after):
                    self.rollbacks = 0       # incident over
                if (it_before // self.save_every
                        != model.iteration_count // self.save_every):
                    # the save cadence was crossed inside the window:
                    # checkpoint at the boundary, where the iterator
                    # cursor equals self._batch and iterator state
                    # rides the zip
                    self.save_checkpoint()
            if exhausted:
                return False

    @staticmethod
    def _chaos_step(ds):
        f = chaos.step_fault("train.step")
        if f is not None and f.kind == "sigterm":
            # a REAL preemption drill: deliver SIGTERM to the process
            # at the seeded ordinal. Under fit()'s handler this takes
            # the grace path (snapshot → persist → clean stop); with
            # no handler installed it kills the process, exactly like
            # the cloud scheduler would
            os.kill(os.getpid(), signal.SIGTERM)
        if f is not None and f.kind == "nan":
            # poison one element of this batch's features (the
            # nan_injection drill, plan-driven): copy-on-write so the
            # source iterator's batch — which the rollback replay
            # will re-fetch — stays clean
            feats = ds.features
            arr = feats[0] if isinstance(feats, (list, tuple)) \
                else feats
            arr = np.array(arr)
            arr.flat[0] = np.nan
            ds = copy.copy(ds)
            if isinstance(feats, (list, tuple)):
                ds.features = type(feats)(
                    [arr] + list(feats[1:]))
            else:
                ds.features = arr
        return ds

    def _rollback(self, skip_ordinal=None):
        self.rollbacks += 1
        self.total_rollbacks += 1
        self._healthy_streak = 0
        if self.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"non-finite loss persisted through "
                f"{self.max_rollbacks} rollbacks — aborting (bad data "
                f"or divergent learning rate)")
        logger.warning("non-finite loss at iteration %d: rolling back "
                       "(rollback %d/%d)",
                       self.model.iteration_count, self.rollbacks,
                       self.max_rollbacks)
        # the batch that produced the non-finite loss: skip it on
        # replay, replay everything else. Per-step callers leave the
        # default (the batch just consumed, ordinal _batch - 1); the
        # k-step window path passes the exact in-window ordinal.
        if skip_ordinal is None:
            skip_ordinal = (self._epoch, self._batch - 1)
        self._skip.add(skip_ordinal)
        # an async save may still be in flight — it IS the newest
        # generation; restoring before it lands would silently roll
        # back further than necessary
        self.checkpoint_barrier()
        # generation-by-generation fallback: a corrupt newest
        # checkpoint must cost one quarantine, not the run
        path = self._restore_latest_intact()
        if path is None:
            raise RuntimeError("non-finite loss and no intact "
                               "checkpoint to roll back to")
        logger.warning("rolled back to %s", path)
        if self.lr_drop_on_rollback:
            self._drop_lr(self.lr_drop_on_rollback)
        # immediately persist the restored state WITH the new skip
        # entry (same iteration ordinal — overwrites in place): a kill
        # right after this rollback resumes skip-aware instead of
        # paying a second rollback to rediscover the poison batch
        self.save_checkpoint()

    def _drop_lr(self, factor: float) -> None:
        """Scale the configured learning rate and rebuild the
        optimizer (restart-with-cooler-LR; optimizer state resets by
        design — the restored momentum pointed at the blow-up)."""
        try:
            cfg = self.model.conf.conf.updater_cfg
            if cfg is None:
                # no explicit updater: the executor trains with the
                # default sgd() — materialize it so the drop applies
                # instead of silently doing nothing
                from deeplearning4j_tpu.nn.conf import updaters
                cfg = updaters.sgd()
                self.model.conf.conf.updater_cfg = cfg
            if not cfg.get("lr"):
                logger.warning(
                    "rollback LR drop skipped: updater config %r has "
                    "no 'lr' to scale", cfg.get("type"))
                return
            old = cfg["lr"]
            cfg["lr"] = old * factor
            if hasattr(self.model, "_build_optimizer"):
                self.model._build_optimizer()
            logger.warning("rollback LR drop: %g -> %g", old,
                           cfg["lr"])
        except Exception:
            logger.exception("LR drop after rollback failed")
