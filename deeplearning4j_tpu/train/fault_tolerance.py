"""Elastic / fault-tolerant training.

The reference's failure handling is minimal by design (SURVEY §5:
InvalidScoreIterationTerminationCondition + Spark task retry; no
elastic membership). On TPU pods the real-world failure modes are
preemption (SIGTERM with a grace window) and numeric blow-ups; the
idiomatic recovery is checkpoint-based restart. :class:`ElasticTrainer`
packages that loop:

- periodic ATOMIC checkpoints (tmp + rename; a preemption mid-write
  never corrupts the latest checkpoint), pruned to ``keep`` newest;
- automatic resume from the newest valid checkpoint on construction;
- SIGTERM → checkpoint immediately and stop cleanly (the TPU
  preemption grace-window contract);
- non-finite loss → roll back to the last checkpoint and continue
  (InvalidScore semantics, but recovering instead of terminating),
  bounded by ``max_rollbacks``.

Works with both executors via the zip serializer.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import time
from typing import Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ElasticTrainer"]

_CKPT_RE = re.compile(r"ckpt_(\d+)\.zip$")


class ElasticTrainer:
    def __init__(self, model, checkpoint_dir: str, *,
                 save_every: int = 100, keep: int = 3,
                 max_rollbacks: int = 5, handle_sigterm: bool = True):
        self.model = model
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.save_every = max(1, save_every)
        self.keep = max(1, keep)
        self.max_rollbacks = max_rollbacks
        self.handle_sigterm = handle_sigterm
        self.rollbacks = 0
        self._stop_requested = False
        self._resume()

    # -- checkpoint plumbing ----------------------------------------------
    def _ckpts(self):
        out = []
        for f in os.listdir(self.dir):
            m = _CKPT_RE.match(f)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, f)))
        return sorted(out)

    def latest_checkpoint(self) -> Optional[str]:
        cks = self._ckpts()
        return cks[-1][1] if cks else None

    def save_checkpoint(self):
        from deeplearning4j_tpu.util.model_serializer import write_model
        it = self.model.iteration_count
        final = os.path.join(self.dir, f"ckpt_{it}.zip")
        tmp = final + f".tmp{os.getpid()}"
        write_model(self.model, tmp)
        os.replace(tmp, final)          # atomic on POSIX
        for _, path in self._ckpts()[:-self.keep]:
            try:
                os.remove(path)
            except OSError:
                pass
        logger.info("checkpoint at iteration %d -> %s", it, final)
        return final

    def _restore_into_model(self, path: str):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        loaded = restore_model(path)
        m = self.model
        m.params = loaded.params
        m.state = loaded.state
        m.opt_state = loaded.opt_state
        m.iteration_count = loaded.iteration_count
        m.epoch_count = loaded.epoch_count

    def _resume(self):
        path = self.latest_checkpoint()
        if path is not None:
            if self.model.params is None:
                self.model.init()
            self._restore_into_model(path)
            logger.info("resumed from %s (iteration %d)", path,
                        self.model.iteration_count)

    # -- the loop -----------------------------------------------------------
    def fit(self, iterator, *, epochs: int = 1) -> "ElasticTrainer":
        model = self.model
        if model.params is None:
            model.init()
        prev_handler = None
        if self.handle_sigterm:
            def on_term(signum, frame):
                # preemption grace window: persist, then stop cleanly
                self._stop_requested = True
            prev_handler = signal.signal(signal.SIGTERM, on_term)
        try:
            if self.latest_checkpoint() is None:
                self.save_checkpoint()       # iteration-0 restart point
            for _ in range(epochs):
                if self._stop_requested:
                    break
                if hasattr(iterator, "reset"):
                    iterator.reset()
                for ds in iterator:
                    if self._stop_requested:
                        break
                    model.fit(ds)
                    loss = float(model.score_value)
                    if not np.isfinite(loss):
                        self._rollback()
                        continue
                    if model.iteration_count % self.save_every == 0:
                        self.save_checkpoint()
            if self._stop_requested:
                self.save_checkpoint()
                logger.warning("stop requested (preemption?): "
                               "checkpointed at iteration %d",
                               model.iteration_count)
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
        return self

    def _rollback(self):
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"non-finite loss persisted through "
                f"{self.max_rollbacks} rollbacks — aborting (bad data "
                f"or divergent learning rate)")
        path = self.latest_checkpoint()
        if path is None:
            raise RuntimeError("non-finite loss and no checkpoint to "
                               "roll back to")
        logger.warning("non-finite loss at iteration %d: rolling back "
                       "to %s (rollback %d/%d)",
                       self.model.iteration_count, path, self.rollbacks,
                       self.max_rollbacks)
        self._restore_into_model(path)
