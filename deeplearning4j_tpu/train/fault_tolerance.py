"""Elastic / fault-tolerant training.

The reference's failure handling is minimal by design (SURVEY §5:
InvalidScoreIterationTerminationCondition + Spark task retry; no
elastic membership). On TPU pods the real-world failure modes are
preemption (SIGTERM with a grace window) and numeric blow-ups; the
idiomatic recovery is checkpoint-based restart. :class:`ElasticTrainer`
packages that loop:

- periodic ATOMIC checkpoints (tmp + rename; a preemption mid-write
  never corrupts the latest checkpoint), pruned to ``keep`` newest;
- the DATA POSITION (epoch index, batch index) rides inside the
  checkpoint zip, so a resumed or rolled-back run fast-forwards the
  iterator to exactly where the checkpointed model stopped —
  kill-at-iteration-k + resume reproduces the uninterrupted run
  bit-for-bit for a deterministic iterator (the reference's
  serialization-regression discipline, SURVEY §4.3, applied here);
- automatic resume from the newest valid checkpoint on construction;
- SIGTERM → checkpoint immediately and stop cleanly (the TPU
  preemption grace-window contract); the handler is only installed on
  the main thread (signal.signal raises elsewhere);
- non-finite loss → roll back to the last checkpoint (model AND data
  position), REPLAY the batches in between, and skip exactly the one
  batch that produced the non-finite loss (InvalidScore-skip semantics:
  a deterministic poison batch must not re-diverge the replay forever).
  Bounded by ``max_rollbacks`` per incident: the rollback counter
  decays to zero after ``heal_after`` consecutive healthy iterations,
  so the bound is per-divergence, not per-lifetime. The SKIP SET is
  persisted: a rollback immediately re-checkpoints (restored params +
  skip), so a process killed right after a rollback resumes
  skip-aware — restart == uninterrupted holds THROUGH rollbacks, not
  just for clean kills;
- the replay fast-forward assumes a DETERMINISTIC same-order
  iterator; that contract is CHECKED, not just documented: each
  checkpoint carries a rolling fingerprint chain over every batch
  consumed this epoch, and a resumed run recomputes the chain over
  the replayed batches — any reorder, substitution, or shortfall in
  ANY replayed ordinal fails loudly instead of silently diverging;
- checkpoint DURABILITY (the chaos PR): every restore first passes
  :func:`~deeplearning4j_tpu.util.model_serializer.verify_checkpoint`
  (zip CRCs + the CRC32 manifest written into every zip); a
  truncated/corrupted generation is QUARANTINED (renamed
  ``*.corrupt``, counted as ``checkpoint_quarantined_total``) and the
  trainer falls back generation by generation to the newest intact
  checkpoint instead of dying on ``BadZipFile``. A failed checkpoint
  WRITE (ENOSPC, quota) is a missed checkpoint, not a dead run: the
  partial tmp is removed, ``checkpoint_write_failures_total`` counts
  it, and training continues on the previous generation. Stale
  ``*.tmp<pid>`` files leaked by a crash mid-write are swept on
  trainer start. The ``train.step`` chaos site fires right before
  each step (crash / hang / nan-poison drills).

Works with both executors via the zip serializer.
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
import os
import re
import signal
import threading
import time
import zipfile
from typing import Optional

import numpy as np

from deeplearning4j_tpu import chaos

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ElasticTrainer"]

_CKPT_RE = re.compile(r"ckpt_(\d+)\.zip$")
_TMP_RE = re.compile(r"ckpt_\d+\.zip\.tmp(\d+)$")
_POS_ENTRY = "data_position.json"


def _hash_array(h, a) -> None:
    a = np.asarray(a)
    flat = a.reshape(-1) if a.flags.c_contiguous else a.ravel()
    k = 256
    n = flat.size
    h.update(str(a.shape).encode())
    h.update(str(a.dtype).encode())
    for window in (flat[:k], flat[n // 2:n // 2 + k],
                   flat[max(0, n - k):]):
        h.update(np.ascontiguousarray(window).tobytes())


def _fingerprint(ds) -> str:
    """Cheap content fingerprint of a batch: shape + dtype + three
    sampled 1KB windows (head / middle / tail) of EVERY feature AND
    label array (all of them for a MultiDataSet). Labels are folded
    in deliberately: a replayed iterator that kept features but
    substituted or reordered labels would otherwise pass the
    determinism check and silently train on wrong targets. Sampling
    windows (not just the head) catches shared-BOS/padding layouts
    whose leading bytes are identical across batches; slicing views
    before ``tobytes`` keeps the copy small regardless of batch
    size."""
    h = hashlib.sha1()
    for group in (ds.features, getattr(ds, "labels", None)):
        if group is None:
            continue
        if not isinstance(group, (list, tuple)):
            group = (group,)
        h.update(b"|g%d" % len(group))
        for slot, a in enumerate(group):
            # per-slot marker even for None: [x, None, y] must not
            # fingerprint equal to [x, y, None]
            h.update(b"|s%d" % slot)
            if a is None:
                h.update(b"<none>")
            else:
                _hash_array(h, a)
    return h.hexdigest()


def _chain(prev: str, fp: str) -> str:
    """Rolling digest over consumed batches: order-sensitive, so a
    replay that reorders ANY prefix batch (not just the last one)
    mismatches."""
    return hashlib.sha1((prev + fp).encode()).hexdigest()


class ElasticTrainer:
    def __init__(self, model, checkpoint_dir: str, *,
                 save_every: int = 100, keep: int = 3,
                 max_rollbacks: int = 5, heal_after: Optional[int] = None,
                 handle_sigterm: bool = True, wrapper=None,
                 lr_drop_on_rollback: Optional[float] = None):
        # lr_drop_on_rollback: multiply the configured learning rate
        # by this factor (< 1) on every rollback — the standard
        # "restart from the last good checkpoint with a cooler LR"
        # move for repeated divergence. Rebuilding the optimizer
        # resets its state (momentum), which is exactly the restart
        # semantics wanted after a blow-up.
        # wrapper: optional ParallelWrapper around ``model`` — batches
        # then train data-parallel while checkpoint/restore still talks
        # to the underlying model (ParallelWrapper.java analog: the
        # wrapper composes with, not replaces, the model's lifecycle)
        self.model = model
        self.wrapper = wrapper
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.save_every = max(1, save_every)
        self.keep = max(1, keep)
        self.max_rollbacks = max_rollbacks
        self.heal_after = (save_every if heal_after is None
                           else max(1, heal_after))
        self.handle_sigterm = handle_sigterm
        self.lr_drop_on_rollback = lr_drop_on_rollback
        self.rollbacks = 0           # current incident (decays)
        self.total_rollbacks = 0     # lifetime (never decays)
        self._healthy_streak = 0
        self._stop_requested = False
        self._epoch = 0          # data position: epoch index
        self._batch = 0          # batches consumed within that epoch
        self._skip = set()       # (epoch, batch) ordinals to skip
        self._fp_chain = ""      # rolling digest of every batch
        #                          consumed this epoch (determinism
        #                          check on replay)
        self._sweep_stale_tmp()
        self._resume()

    # -- checkpoint plumbing ----------------------------------------------
    def _ckpts(self):
        out = []
        for f in os.listdir(self.dir):
            m = _CKPT_RE.match(f)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, f)))
        return sorted(out)

    def latest_checkpoint(self) -> Optional[str]:
        cks = self._ckpts()
        return cks[-1][1] if cks else None

    def _sweep_stale_tmp(self) -> None:
        """A crash mid-``write_model`` leaks ``ckpt_N.zip.tmp<pid>``
        forever (the pid suffix means a restarted process never
        collides with, and so never cleans, the old name); sweep them
        on start — but only when the owning pid is dead, so a second
        trainer pointed at a shared directory can never delete a
        write another live process is mid-way through."""
        for f in os.listdir(self.dir):
            m = _TMP_RE.match(f)
            if not m:
                continue
            pid = int(m.group(1))
            if pid != os.getpid():
                try:
                    os.kill(pid, 0)      # probe: is the owner alive?
                    continue             # yes — not ours to sweep
                except ProcessLookupError:
                    pass                 # dead owner: stale for sure
                except OSError:
                    continue             # EPERM etc.: assume alive
            path = os.path.join(self.dir, f)
            try:
                os.remove(path)
                logger.info("swept stale checkpoint tmp %s", path)
            except OSError:
                pass

    def save_checkpoint(self):
        from deeplearning4j_tpu.util.model_serializer import write_model
        it = self.model.iteration_count
        final = os.path.join(self.dir, f"ckpt_{it}.zip")
        tmp = final + f".tmp{os.getpid()}"
        # the data position rides in the same zip: one atomic artifact,
        # no model/position skew after a mid-write preemption; passing
        # it through write_model (not appending after) puts it under
        # the integrity manifest's CRC too
        pos = json.dumps(
            {"epoch": self._epoch, "batch": self._batch,
             # the poison-skip set rides in the checkpoint: a
             # restart after a rollback must not pay a second
             # rollback to rediscover a deterministic poison batch
             "skip": sorted(list(p) for p in self._skip),
             "fp_chain": self._fp_chain})
        try:
            write_model(self.model, tmp,
                        extra_entries={_POS_ENTRY: pos})
            os.replace(tmp, final)      # atomic on POSIX
        except OSError as e:
            # ENOSPC / quota / dying disk mid-write: a missed
            # checkpoint must not kill the run — clean the partial
            # tmp, count it, and keep training on the previous
            # generation
            try:
                os.remove(tmp)
            except OSError:
                pass
            self._count("checkpoint_write_failures_total",
                        "checkpoint writes that failed (ENOSPC, ...)")
            logger.warning("checkpoint write at iteration %d failed "
                           "(%r); continuing on the previous "
                           "generation", it, e)
            return None
        # mark live trainer checkpoints protected so a co-attached
        # CheckpointListener's keep_last pruning can never delete the
        # file a rollback is about to restore
        from deeplearning4j_tpu.train import listeners as _listeners
        _listeners.protect_checkpoint(final)
        for _, path in self._ckpts()[:-self.keep]:
            try:
                os.remove(path)
            except OSError:
                pass
            _listeners.unprotect_checkpoint(path)
        logger.info("checkpoint at iteration %d (epoch %d, batch %d) "
                    "-> %s", it, self._epoch, self._batch, final)
        return final

    @staticmethod
    def _count(name: str, help: str) -> None:
        from deeplearning4j_tpu.observability.registry import safe_inc
        safe_inc(name, help=help)

    def _restore_into_model(self, path: str):
        from deeplearning4j_tpu.util.model_serializer import (
            restore_model, verify_checkpoint)
        verify_checkpoint(path)    # CRC gate BEFORE trusting the zip
        loaded = restore_model(path)
        m = self.model
        m.params = loaded.params
        m.state = loaded.state
        m.opt_state = loaded.opt_state
        m.iteration_count = loaded.iteration_count
        m.epoch_count = loaded.epoch_count
        try:
            with zipfile.ZipFile(path, "r") as z:
                pos = json.loads(z.read(_POS_ENTRY))
            self._epoch = int(pos["epoch"])
            self._batch = int(pos["batch"])
            # MERGE the persisted skip set (a rollback restores an
            # older checkpoint whose zip may predate the newest skip
            # entry — skips are monotone within an incident)
            self._skip |= {tuple(p) for p in pos.get("skip", [])}
            self._fp_chain = pos.get("fp_chain") or ""
        except (KeyError, json.JSONDecodeError):
            # pre-position checkpoint (older format): restart the epoch
            self._epoch, self._batch = 0, 0

    def _quarantine(self, path: str, err: BaseException) -> None:
        """Rename a checkpoint that failed verification/restore to
        ``*.corrupt`` — out of the generation sequence (so fallback
        terminates) but kept on disk as evidence."""
        from deeplearning4j_tpu.train import listeners as _listeners
        q = path + ".corrupt"
        logger.warning("checkpoint %s failed integrity/restore (%r): "
                       "quarantining as %s and falling back to the "
                       "previous generation", path, err, q)
        try:
            os.replace(path, q)
        except FileNotFoundError:
            return              # already gone — nothing to quarantine
        except OSError:
            # last resort: a file we can neither rename nor remove
            # would make the fallback loop spin forever
            try:
                os.remove(path)
            except FileNotFoundError:
                return
        _listeners.unprotect_checkpoint(path)
        self._count("checkpoint_quarantined_total",
                    "corrupt/truncated checkpoints quarantined on "
                    "restore")

    def _restore_latest_intact(self) -> Optional[str]:
        """Restore the newest checkpoint that passes verification,
        quarantining corrupt generations on the way down; None when
        no intact generation remains."""
        from deeplearning4j_tpu.chaos.retry import DEFAULT_IO_RETRY
        from deeplearning4j_tpu.util.model_serializer import (
            CheckpointIntegrityError)
        while True:
            path = self.latest_checkpoint()
            if path is None:
                return None
            try:
                # transient read errors (NFS blip, injected IOError)
                # get the shared retry policy FIRST — a healthy file
                # must not be quarantined for a flaky read
                DEFAULT_IO_RETRY.call(self._restore_into_model, path)
                return path
            except (CheckpointIntegrityError, zipfile.BadZipFile,
                    OSError, KeyError, ValueError) as e:
                # BadZipFile/OSError/ValueError: rot the CRC gate
                # could not see (or chaos injected mid-read);
                # KeyError: arrays missing vs this model's config
                self._quarantine(path, e)

    def _resume(self):
        if not self._ckpts():
            return
        if self.model.params is None:
            self.model.init()
        path = self._restore_latest_intact()
        if path is None:
            logger.warning("no intact checkpoint in %s; starting "
                           "fresh", self.dir)
            return
        logger.info("resumed from %s (iteration %d, epoch %d, "
                    "batch %d)", path, self.model.iteration_count,
                    self._epoch, self._batch)

    # -- the loop -----------------------------------------------------------
    def fit(self, iterator, *, epochs: int = 1,
            until_epoch: Optional[int] = None) -> "ElasticTrainer":
        """``epochs`` is RELATIVE (train N more epochs from wherever
        the trainer is — a resumed trainer continues); ``until_epoch``
        is an ABSOLUTE target epoch index: rerunning the same
        ``fit(until_epoch=N)`` command after a kill produces exactly
        the uninterrupted run (restart == uninterrupted)."""
        target = (self._epoch + max(0, epochs)
                  if until_epoch is None else until_epoch)
        model = self.model
        if model.params is None:
            model.init()
        prev_handler = None
        if (self.handle_sigterm
                and threading.current_thread() is threading.main_thread()):
            def on_term(signum, frame):
                # preemption grace window: persist, then stop cleanly
                self._stop_requested = True
            prev_handler = signal.signal(signal.SIGTERM, on_term)
        elif self.handle_sigterm:
            logger.info("fit() on a non-main thread: SIGTERM handler "
                        "not installed (signal.signal would raise)")
        try:
            if self.latest_checkpoint() is None:
                self.save_checkpoint()       # iteration-0 restart point
            while self._epoch < target and not self._stop_requested:
                if hasattr(iterator, "reset"):
                    iterator.reset()
                it = iter(iterator)
                # fast-forward a resumed/rolled-back run to the
                # checkpointed batch — restart == uninterrupted for a
                # deterministic iterator; the rolling fingerprint
                # chain CHECKS that contract over EVERY replayed
                # ordinal (any reorder or shortfall mismatches)
                fwd_chain = ""
                for k in range(self._batch):
                    ds = next(it, None)
                    if ds is None:
                        fwd_chain = None
                        break
                    fwd_chain = _chain(fwd_chain, _fingerprint(ds))
                if (self._batch and self._fp_chain
                        and fwd_chain != self._fp_chain):
                    raise RuntimeError(
                        f"iterator is not deterministic: the "
                        f"{self._batch} batches replayed for epoch "
                        f"{self._epoch} differ from the ones consumed "
                        f"before the restart — the replay "
                        f"fast-forward requires a same-order iterator "
                        f"(disable shuffling or seed it per-epoch)")
                rolled_back = False
                for ds in it:
                    if self._stop_requested:
                        break
                    self._fp_chain = _chain(self._fp_chain,
                                            _fingerprint(ds))
                    if (self._epoch, self._batch) in self._skip:
                        self._batch += 1     # the poisoned batch
                        continue
                    # chaos site: crash raises (a simulated
                    # preemption — resume must reproduce the
                    # uninterrupted run), hang sleeps, nan poisons
                    # this one batch (exercising the rollback path)
                    ds = self._chaos_step(ds)
                    try:
                        if self.wrapper is not None:
                            self.wrapper.fit([ds])
                        else:
                            model.fit(ds)
                    except Exception as e:
                        # HealthMonitor's rollback policy raises a
                        # rollback-flagged TrainingDivergedError from
                        # the listener chain: restore the last good
                        # checkpoint and continue, same as a
                        # non-finite loss. Anything else propagates.
                        if not getattr(e, "rollback", False):
                            raise
                        self._batch += 1     # batch was consumed
                        logger.warning(
                            "health monitor requested rollback: %s", e)
                        self._rollback()
                        rolled_back = True
                        break
                    self._batch += 1
                    loss = float(model.score_value)
                    if not np.isfinite(loss):
                        self._rollback()
                        rolled_back = True
                        break            # re-enter at restored position
                    self._healthy_streak += 1
                    if (self.rollbacks
                            and self._healthy_streak >= self.heal_after):
                        self.rollbacks = 0   # incident over
                    if model.iteration_count % self.save_every == 0:
                        self.save_checkpoint()
                if rolled_back or self._stop_requested:
                    continue
                self._epoch += 1
                self._batch = 0
                self._fp_chain = ""
            if self._stop_requested:
                self.save_checkpoint()
                logger.warning("stop requested (preemption?): "
                               "checkpointed at iteration %d",
                               model.iteration_count)
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
        return self

    @staticmethod
    def _chaos_step(ds):
        f = chaos.step_fault("train.step")
        if f is not None and f.kind == "nan":
            # poison one element of this batch's features (the
            # nan_injection drill, plan-driven): copy-on-write so the
            # source iterator's batch — which the rollback replay
            # will re-fetch — stays clean
            feats = ds.features
            arr = feats[0] if isinstance(feats, (list, tuple)) \
                else feats
            arr = np.array(arr)
            arr.flat[0] = np.nan
            ds = copy.copy(ds)
            if isinstance(feats, (list, tuple)):
                ds.features = type(feats)(
                    [arr] + list(feats[1:]))
            else:
                ds.features = arr
        return ds

    def _rollback(self):
        self.rollbacks += 1
        self.total_rollbacks += 1
        self._healthy_streak = 0
        if self.rollbacks > self.max_rollbacks:
            raise RuntimeError(
                f"non-finite loss persisted through "
                f"{self.max_rollbacks} rollbacks — aborting (bad data "
                f"or divergent learning rate)")
        logger.warning("non-finite loss at iteration %d: rolling back "
                       "(rollback %d/%d)",
                       self.model.iteration_count, self.rollbacks,
                       self.max_rollbacks)
        # the batch just consumed (ordinal _batch - 1) produced the
        # non-finite loss: skip it on replay, replay everything else
        self._skip.add((self._epoch, self._batch - 1))
        # generation-by-generation fallback: a corrupt newest
        # checkpoint must cost one quarantine, not the run
        path = self._restore_latest_intact()
        if path is None:
            raise RuntimeError("non-finite loss and no intact "
                               "checkpoint to roll back to")
        logger.warning("rolled back to %s", path)
        if self.lr_drop_on_rollback:
            self._drop_lr(self.lr_drop_on_rollback)
        # immediately persist the restored state WITH the new skip
        # entry (same iteration ordinal — overwrites in place): a kill
        # right after this rollback resumes skip-aware instead of
        # paying a second rollback to rediscover the poison batch
        self.save_checkpoint()

    def _drop_lr(self, factor: float) -> None:
        """Scale the configured learning rate and rebuild the
        optimizer (restart-with-cooler-LR; optimizer state resets by
        design — the restored momentum pointed at the blow-up)."""
        try:
            cfg = self.model.conf.conf.updater_cfg
            if cfg is None:
                # no explicit updater: the executor trains with the
                # default sgd() — materialize it so the drop applies
                # instead of silently doing nothing
                from deeplearning4j_tpu.nn.conf import updaters
                cfg = updaters.sgd()
                self.model.conf.conf.updater_cfg = cfg
            if not cfg.get("lr"):
                logger.warning(
                    "rollback LR drop skipped: updater config %r has "
                    "no 'lr' to scale", cfg.get("type"))
                return
            old = cfg["lr"]
            cfg["lr"] = old * factor
            if hasattr(self.model, "_build_optimizer"):
                self.model._build_optimizer()
            logger.warning("rollback LR drop: %g -> %g", old,
                           cfg["lr"])
        except Exception:
            logger.exception("LR drop after rollback failed")
