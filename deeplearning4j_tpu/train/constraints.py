"""Parameter constraints — post-update projections.

Mirrors nn/conf/constraint/*.java (MaxNormConstraint,
MinMaxNormConstraint, NonNegativeConstraint, UnitNormConstraint),
applied after each optimizer step (reference:
StochasticGradientDescent.java:96 applyConstraints). Config form:
``{"type": "max_norm", "max_norm": 2.0}`` etc.; constraints attach to a
layer config's ``constraints`` tuple and are applied to its weight
params ("W"-like keys, not biases, matching the reference default
applyToWeights=true/applyToBiases=false).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["apply_constraint", "apply_layer_constraints"]

_EPS = 1e-8


def _norms(w, axis):
    return jnp.sqrt(jnp.sum(w * w, axis=axis, keepdims=True))


def apply_constraint(w, cfg: dict):
    t = cfg["type"]
    # norm over all axes but the last (output dim) — matches the
    # reference's dimension convention for dense/conv weights
    axis = tuple(range(w.ndim - 1)) or (0,)
    if t == "max_norm":
        n = _norms(w, axis)
        target = jnp.minimum(n, cfg.get("max_norm", 2.0))
        return w * target / (n + _EPS)
    if t == "min_max_norm":
        lo = cfg.get("min_norm", 0.0)
        hi = cfg.get("max_norm", 2.0)
        rate = cfg.get("rate", 1.0)
        n = _norms(w, axis)
        clipped = jnp.clip(n, lo, hi)
        scaled = w * (rate * clipped / (n + _EPS) + (1 - rate))
        return scaled
    if t == "non_negative":
        return jnp.maximum(w, 0.0)
    if t == "unit_norm":
        return w / (_norms(w, axis) + _EPS)
    raise ValueError(f"Unknown constraint type '{t}'")


def apply_layer_constraints(layer_cfg, layer_params: dict) -> dict:
    if not getattr(layer_cfg, "constraints", None):
        return layer_params
    out = dict(layer_params)
    for cfg in layer_cfg.constraints:
        apply_b = cfg.get("apply_to_biases", False)
        apply_w = cfg.get("apply_to_weights", True)
        for k, v in out.items():
            is_bias = k in ("b", "vb", "beta")
            if (is_bias and apply_b) or (not is_bias and apply_w
                                         and v.ndim >= 2):
                out[k] = apply_constraint(v, cfg)
    return out
