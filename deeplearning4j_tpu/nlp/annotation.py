"""Composable text-annotation pipeline — the UIMA-module analog.

The reference ships ``deeplearning4j-nlp-uima``: annotators
(SentenceAnnotator.java, TokenizerAnnotator.java, StemmerAnnotator.java)
composed as UIMA analysis-engine pipelines over a shared CAS document,
plus tokenizer factories that expose a pipeline through the
tokenization SPI (UimaTokenizerFactory.java:40-76). What is
architecturally load-bearing is the COMPOSITION model: each annotator
reads the document plus previously-added span annotations and adds its
own layer. This module is that model without the UIMA machinery:

- :class:`AnnotatedDocument` — text + typed span annotations (the CAS
  analog, a plain data object);
- :class:`Annotator` — the analysis-engine SPI (``process(doc)``);
- :class:`SentenceAnnotator` — rule-based sentence spans (the
  reference wraps an OpenNLP statistical model; the rule-based
  splitter keeps the pack self-contained — no model files);
- :class:`TokenizerAnnotator` — token spans inside sentence spans,
  driven by ANY TokenizerFactory (including the lattice CJK packs);
- :class:`StemmerAnnotator` — Porter stems as token features
  (StemmerAnnotator.java wraps Snowball; Porter is its English core);
- :class:`AnnotatorPipeline` — ordered composition;
- :class:`AnnotationTokenizerFactory` — exposes a pipeline through
  the tokenization SPI, the UimaTokenizerFactory analog.

De-scoped knowingly (see COMPONENTS.md): the treeparser corner
(corpora/treeparser — constituency trees need a parser model the
reference gets from ClearTK/OpenNLP), SentiWordNet scoring (SWN3.java
wraps a 13MB lexicon), and model-file-based POS tagging. Each wraps
an external model artifact rather than framework machinery.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional

__all__ = ["Annotation", "AnnotatedDocument", "Annotator",
           "SentenceAnnotator", "TokenizerAnnotator",
           "StemmerAnnotator", "AnnotatorPipeline",
           "AnnotationTokenizerFactory", "porter_stem"]


@dataclasses.dataclass
class Annotation:
    """A typed span over the document text (the UIMA Annotation
    analog). ``features`` carries annotator-added attributes (e.g.
    the stem of a token)."""
    type: str
    begin: int
    end: int
    features: Dict[str, str] = dataclasses.field(default_factory=dict)

    def covered_text(self, text: str) -> str:
        return text[self.begin:self.end]


class AnnotatedDocument:
    """Text + annotation layers (the CAS analog)."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []

    def add(self, ann: Annotation) -> None:
        self.annotations.append(ann)

    def select(self, type_: str) -> List[Annotation]:
        """Annotations of a type, in document order."""
        return sorted((a for a in self.annotations if a.type == type_),
                      key=lambda a: (a.begin, a.end))

    def covered(self, ann: Annotation, type_: str) -> List[Annotation]:
        """Annotations of ``type_`` inside ``ann``'s span (UIMA's
        selectCovered)."""
        return [a for a in self.select(type_)
                if a.begin >= ann.begin and a.end <= ann.end]


class Annotator:
    """Analysis-engine SPI: mutate ``doc`` by adding annotations."""

    def process(self, doc: AnnotatedDocument) -> None:
        raise NotImplementedError


_ABBREV = frozenset({
    "mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc",
    "e.g", "i.e", "fig", "al", "inc", "ltd", "co", "corp", "no",
    "vol", "pp", "approx", "dept", "est", "min", "max"})

_SENT_BOUNDARY = re.compile(r"[.!?。！？]+[\"'”’)\]]*\s+|[.!?。！？]+[\"'”’)\]]*$")


class SentenceAnnotator(Annotator):
    """Sentence spans via punctuation rules with an abbreviation
    guard (the SentenceAnnotator.java slot; rule-based so no model
    file ships). Handles ASCII and CJK terminators."""

    def process(self, doc: AnnotatedDocument) -> None:
        text = doc.text
        start = 0
        for m in _SENT_BOUNDARY.finditer(text):
            # abbreviation guard: 'Dr. Smith' must not split
            head = text[start:m.start() + 1]
            last = re.split(r"\s+", head.strip())[-1] if head.strip() \
                else ""
            word = last.rstrip(".").lower()
            if last.endswith(".") and (word in _ABBREV
                                       or (len(word) == 1
                                           and word.isalpha())):
                continue
            end = m.end()
            seg = text[start:end].strip()
            if seg:
                b = start + (len(text[start:end])
                             - len(text[start:end].lstrip()))
                doc.add(Annotation("sentence", b, b + len(seg)))
            start = end
        tail = text[start:].strip()
        if tail:
            b = start + (len(text[start:]) - len(text[start:].lstrip()))
            doc.add(Annotation("sentence", b, b + len(tail)))


class TokenizerAnnotator(Annotator):
    """Token spans inside each sentence span, via any
    TokenizerFactory (TokenizerAnnotator.java slot — and because the
    factory is pluggable, the lattice zh/ja/ko packs ride the same
    pipeline). Runs document-wide if no sentence annotations exist."""

    def __init__(self, tokenizer_factory=None):
        if tokenizer_factory is None:
            from deeplearning4j_tpu.nlp.tokenization import (
                DefaultTokenizerFactory)
            tokenizer_factory = DefaultTokenizerFactory()
        self.factory = tokenizer_factory

    _PUNCT = ".,;:!?\"'`()[]{}«»„“”‘’—–…。、，！？；：（）「」『』"

    def process(self, doc: AnnotatedDocument) -> None:
        spans = doc.select("sentence") or [
            Annotation("sentence", 0, len(doc.text))]
        for s in spans:
            seg = s.covered_text(doc.text)
            pos = 0
            for tok in self.factory.create(seg).get_tokens():
                found = seg.find(tok, pos)
                if found < 0:        # preprocessor rewrote the token:
                    #                  anchor best-effort at `pos`
                    found = pos
                pos = found + len(tok)
                # surrounding punctuation stays out of the token span
                # (the UIMA/ClearTK tokenizers emit punctuation
                # separately; the whitespace default does not)
                core = tok.strip(self._PUNCT)
                if not core:
                    continue
                off = tok.find(core)
                doc.add(Annotation(
                    "token", s.begin + found + off,
                    s.begin + found + off + len(core)))


class StemmerAnnotator(Annotator):
    """Adds a ``stem`` feature to every token annotation
    (StemmerAnnotator.java slot; Porter instead of Snowball-English —
    same algorithm family, self-contained)."""

    def process(self, doc: AnnotatedDocument) -> None:
        for tok in doc.select("token"):
            tok.features["stem"] = porter_stem(
                tok.covered_text(doc.text))


class AnnotatorPipeline(Annotator):
    """Ordered composition (the analysis-engine aggregate):
    ``AnnotatorPipeline([SentenceAnnotator(), TokenizerAnnotator(),
    StemmerAnnotator()]).annotate(text)``."""

    def __init__(self, annotators: Iterable[Annotator]):
        self.annotators = list(annotators)

    def process(self, doc: AnnotatedDocument) -> None:
        for a in self.annotators:
            a.process(doc)

    def annotate(self, text: str) -> AnnotatedDocument:
        doc = AnnotatedDocument(text)
        self.process(doc)
        return doc


class AnnotationTokenizerFactory:
    """TokenizerFactory SPI over an annotator pipeline
    (UimaTokenizerFactory.java:40-76 analog): tokenize() runs
    sentence + token annotators and returns token texts — or their
    ``stem`` feature with ``use_stems=True`` (the
    PosUimaTokenizerFactory pattern of reading a feature instead of
    the surface form)."""

    def __init__(self, pipeline: Optional[AnnotatorPipeline] = None,
                 *, use_stems: bool = False):
        if pipeline is None:
            anns: List[Annotator] = [SentenceAnnotator(),
                                     TokenizerAnnotator()]
            if use_stems:
                anns.append(StemmerAnnotator())
            pipeline = AnnotatorPipeline(anns)
        self.pipeline = pipeline
        self.use_stems = use_stems
        self._pre = None

    def set_token_pre_processor(self, pre) -> None:
        self._pre = pre

    def create(self, text: str):
        from deeplearning4j_tpu.nlp.tokenization import Tokenizer
        doc = self.pipeline.annotate(text)
        toks = []
        for t in doc.select("token"):
            if self.use_stems and "stem" in t.features:
                toks.append(t.features["stem"])
            else:
                toks.append(t.covered_text(doc.text))
        return Tokenizer(toks, self._pre)


# ---------------------------------------------------------------------------
# Porter stemmer — implemented from the published algorithm (Porter,
# "An algorithm for suffix stripping", 1980). Self-contained so the
# stemming annotator needs no external lexicon.
# ---------------------------------------------------------------------------

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """The [C](VC)^m[V] measure."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        if _is_cons(stem, i):
            if prev_vowel:
                m += 1
            prev_vowel = False
        else:
            prev_vowel = True
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(stem: str) -> bool:
    return (len(stem) >= 2 and stem[-1] == stem[-2]
            and _is_cons(stem, len(stem) - 1))


def _cvc(stem: str) -> bool:
    if len(stem) < 3:
        return False
    return (_is_cons(stem, len(stem) - 3)
            and not _is_cons(stem, len(stem) - 2)
            and _is_cons(stem, len(stem) - 1)
            and stem[-1] not in "wxy")


def porter_stem(word: str) -> str:
    w = word.lower()
    if len(w) <= 2 or not w.isalpha():
        return w
    # step 1a
    for suf, rep in (("sses", "ss"), ("ies", "i"), ("ss", "ss"),
                     ("s", "")):
        if w.endswith(suf):
            w = w[:-len(suf)] + rep
            break
    # step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        hit = None
        for suf in ("ed", "ing"):
            if w.endswith(suf) and _has_vowel(w[:-len(suf)]):
                hit = suf
                break
        if hit:
            w = w[:-len(hit)]
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_cons(w) and w[-1] not in "lsz":
                w = w[:-1]
            elif _measure(w) == 1 and _cvc(w):
                w += "e"
    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in (("ational", "ate"), ("tional", "tion"),
                     ("enci", "ence"), ("anci", "ance"),
                     ("izer", "ize"), ("abli", "able"),
                     ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                     ("ousli", "ous"), ("ization", "ize"),
                     ("ation", "ate"), ("ator", "ate"),
                     ("alism", "al"), ("iveness", "ive"),
                     ("fulness", "ful"), ("ousness", "ous"),
                     ("aliti", "al"), ("iviti", "ive"),
                     ("biliti", "ble")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 3
    for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                     ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                     ("ness", "")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible",
                "ant", "ement", "ment", "ent", "ou", "ism", "ate",
                "iti", "ous", "ive", "ize"):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 1:
                w = w[:-len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st" \
                and _measure(w[:-3]) > 1:
            w = w[:-3]
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = _measure(stem)
        if m > 1 or (m == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w
