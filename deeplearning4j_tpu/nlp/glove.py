"""GloVe embeddings.

Mirrors models/glove/Glove.java (429 LoC) +
learning/impl/elements/GloVe.java: co-occurrence matrix with 1/distance
weighting within a window, then the weighted least-squares objective
  J = Σ f(X_ij)(wᵢᵀw̃ⱼ + bᵢ + b̃ⱼ − log X_ij)²,   f(x)=(x/x_max)^α
trained with AdaGrad — but batched over all non-zero co-occurrences in
one jitted step, not per-pair HOGWILD.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabConstructor
from deeplearning4j_tpu.nlp.word2vec import SequenceVectors

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["Glove"]


class Glove(SequenceVectors):
    def __init__(self, *, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, **kw):
        kw.setdefault("learning_rate", 0.05)
        super().__init__(**kw)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.bias_w: Optional[np.ndarray] = None
        self.bias_c: Optional[np.ndarray] = None

    def _cooccurrences(self, sequences) -> Dict[Tuple[int, int], float]:
        counts: Dict[Tuple[int, int], float] = {}
        for seq in sequences:
            idxs = [self.vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0]
            for pos, w in enumerate(idxs):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idxs):
                        break
                    c = idxs[j]
                    inc = 1.0 / off        # 1/distance weighting
                    counts[(w, c)] = counts.get((w, c), 0.0) + inc
                    if self.symmetric:
                        counts[(c, w)] = counts.get((c, w), 0.0) + inc
        return counts

    def fit(self, sequences: List[List[str]]):
        if self.vocab is None:
            self.build_vocab(sequences)
        co = self._cooccurrences(sequences)
        if not co:
            raise ValueError("No co-occurrences found")
        rows = np.array([k[0] for k in co], np.int32)
        cols = np.array([k[1] for k in co], np.int32)
        vals = np.array(list(co.values()), np.float32)
        logv = np.log(vals)
        weights = np.minimum(1.0, (vals / self.x_max) ** self.alpha) \
            .astype(np.float32)

        V, D = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        w = jnp.asarray(((rng.random((V, D)) - 0.5) / D)
                        .astype(np.float32))
        wc = jnp.asarray(((rng.random((V, D)) - 0.5) / D)
                         .astype(np.float32))
        bw = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        # AdaGrad accumulators
        gw = jnp.full((V, D), 1e-8, jnp.float32)
        gwc = jnp.full((V, D), 1e-8, jnp.float32)
        gbw = jnp.full((V,), 1e-8, jnp.float32)
        gbc = jnp.full((V,), 1e-8, jnp.float32)

        rows_j = jnp.asarray(rows)
        cols_j = jnp.asarray(cols)
        logv_j = jnp.asarray(logv)
        wgt_j = jnp.asarray(weights)
        lr = self.learning_rate

        @jax.jit
        def epoch_step(w, wc, bw, bc, gw, gwc, gbw, gbc):
            def loss_fn(w, wc, bw, bc):
                wi = jnp.take(w, rows_j, axis=0)
                cj = jnp.take(wc, cols_j, axis=0)
                pred = (jnp.sum(wi * cj, axis=-1)
                        + jnp.take(bw, rows_j) + jnp.take(bc, cols_j))
                err = pred - logv_j
                return 0.5 * jnp.sum(wgt_j * err * err)
            loss, grads = jax.value_and_grad(loss_fn, (0, 1, 2, 3))(
                w, wc, bw, bc)
            dw, dwc, dbw, dbc = grads
            gw2 = gw + dw * dw
            gwc2 = gwc + dwc * dwc
            gbw2 = gbw + dbw * dbw
            gbc2 = gbc + dbc * dbc
            w2 = w - lr * dw / jnp.sqrt(gw2)
            wc2 = wc - lr * dwc / jnp.sqrt(gwc2)
            bw2 = bw - lr * dbw / jnp.sqrt(gbw2)
            bc2 = bc - lr * dbc / jnp.sqrt(gbc2)
            return w2, wc2, bw2, bc2, gw2, gwc2, gbw2, gbc2, loss

        loss = None
        for ep in range(max(self.epochs, 1)):
            (w, wc, bw, bc, gw, gwc, gbw, gbc,
             loss) = epoch_step(w, wc, bw, bc, gw, gwc, gbw, gbc)
        logger.info("GloVe fit: %d cooccurrences, final loss %.4f",
                    len(vals), float(loss))
        # final embedding = w + context (GloVe convention)
        self.syn0 = np.asarray(w + wc)
        self.syn1 = np.asarray(wc)
        self.bias_w = np.asarray(bw)
        self.bias_c = np.asarray(bc)
        return self
