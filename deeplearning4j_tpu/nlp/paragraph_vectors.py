"""ParagraphVectors (doc2vec).

Mirrors models/paragraphvectors/ParagraphVectors.java (1449 LoC):
PV-DBOW (doc vector predicts words — the reference's DBOW sequence
algorithm, learning/impl/sequence/DBOW.java) and PV-DM (doc + context
mean predicts center, DM.java). Document vectors live in a separate
table; inference of a new doc's vector freezes word/softmax weights
and gradient-descends only the doc vector (reference
inferVector semantics).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.word2vec import SequenceVectors

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["ParagraphVectors"]


class ParagraphVectors(SequenceVectors):
    def __init__(self, *, dm: bool = False, **kw):
        super().__init__(**kw)
        self.dm = dm
        self.doc_vectors: Optional[np.ndarray] = None
        self.doc_labels: List[str] = []
        self._label_index: Dict[str, int] = {}

    def fit_documents(self, documents: Sequence, labels=None):
        """documents: list of token lists; labels default doc_0..n."""
        documents = [list(d) for d in documents]
        labels = (list(labels) if labels is not None
                  else [f"doc_{i}" for i in range(len(documents))])
        self.doc_labels = labels
        self._label_index = {l: i for i, l in enumerate(labels)}
        self.build_vocab(documents)
        rng = np.random.default_rng(self.seed)
        D = self.layer_size
        self.doc_vectors = ((rng.random((len(documents), D)) - 0.5)
                            / D).astype(np.float32)

        pairs = []          # (doc_idx, center, [context for DM])
        for di, doc in enumerate(documents):
            idxs = [self.vocab.index_of(t) for t in doc]
            idxs = [i for i in idxs if i >= 0]
            for pos, center in enumerate(idxs):
                if self.dm:
                    lo = max(0, pos - self.window)
                    hi = min(len(idxs), pos + self.window + 1)
                    ctx = [idxs[j] for j in range(lo, hi) if j != pos]
                    if not ctx:
                        continue
                    ctx = (ctx * self.window)[:self.window]
                    pairs.append((di, center, ctx))
                else:
                    pairs.append((di, center, None))

        step = self._make_doc_step()
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self.syn1)
        docs = jnp.asarray(self.doc_vectors)
        V = len(self.vocab)
        B = min(self.batch_size, max(1, len(pairs)))
        total_steps = max(1, len(pairs) * self.epochs // B)
        step_i = 0
        for _ in range(self.epochs):
            if not pairs:
                continue
            order = rng.permutation(len(pairs))
            if len(pairs) < B:
                order = np.resize(order, B)
            for s in range(0, len(order) - B + 1, B):
                sel = order[s:s + B]
                di = jnp.asarray([pairs[i][0] for i in sel], jnp.int32)
                ce = jnp.asarray([pairs[i][1] for i in sel], jnp.int32)
                if self.dm:
                    cx = jnp.asarray([pairs[i][2] for i in sel],
                                     jnp.int32)
                else:
                    cx = None
                negs = jnp.asarray(
                    rng.choice(V, size=(len(sel), self.negative),
                               p=self._unigram_table), jnp.int32)
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1 - step_i / total_steps))
                docs, syn0, syn1, loss = step(docs, syn0, syn1, di, ce,
                                              cx, negs, jnp.float32(lr))
                step_i += 1
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        self.doc_vectors = np.asarray(docs)
        return self

    def _make_doc_step(self):
        dm = self.dm

        @jax.jit
        def step(docs, syn0, syn1, doc_idx, centers, contexts, negatives,
                 lr):
            def loss_fn(dv, s0, s1):
                d = jnp.take(dv, doc_idx, axis=0)            # (B,D)
                if dm:
                    ctx = jnp.take(s0, contexts, axis=0)     # (B,W,D)
                    h = (d + jnp.sum(ctx, axis=1)) / (1 + ctx.shape[1])
                else:
                    h = d
                pos = jnp.take(s1, centers, axis=0)
                neg = jnp.take(s1, negatives, axis=0)
                pos_score = jnp.sum(h * pos, axis=-1)
                neg_score = jnp.einsum("bd,bkd->bk", h, neg)
                return (jnp.sum(jax.nn.softplus(-pos_score))
                        + jnp.sum(jax.nn.softplus(neg_score)))
            loss, (gd, g0, g1) = jax.value_and_grad(
                loss_fn, (0, 1, 2))(docs, syn0, syn1)
            from deeplearning4j_tpu.nlp.word2vec import _clip_rows
            return (docs - lr * _clip_rows(gd),
                    syn0 - lr * _clip_rows(g0),
                    syn1 - lr * _clip_rows(g1), loss)

        return step

    # ------------------------------------------------------------- queries
    def get_doc_vector(self, label: str) -> Optional[np.ndarray]:
        i = self._label_index.get(label)
        return None if i is None else self.doc_vectors[i]

    def infer_vector(self, tokens: List[str], steps: int = 50,
                     lr: float = 0.05) -> np.ndarray:
        """Infer an unseen document's vector with word weights frozen
        (reference inferVector)."""
        idxs = [self.vocab.index_of(t) for t in tokens]
        idxs = [i for i in idxs if i >= 0]
        if not idxs:
            return np.zeros(self.layer_size, np.float32)
        rng = np.random.default_rng(self.seed)
        v = jnp.asarray(((rng.random(self.layer_size) - 0.5)
                         / self.layer_size).astype(np.float32))
        syn1 = jnp.asarray(self.syn1)
        centers = jnp.asarray(idxs, jnp.int32)
        V = len(self.vocab)

        @jax.jit
        def infer_step(v, negs, lr_):
            def loss_fn(vv):
                pos = jnp.take(syn1, centers, axis=0)
                neg = jnp.take(syn1, negs, axis=0)
                pos_score = pos @ vv
                neg_score = neg @ vv
                return (jnp.mean(jax.nn.softplus(-pos_score))
                        + jnp.mean(jnp.sum(jax.nn.softplus(neg_score),
                                           axis=-1)))
            loss, g = jax.value_and_grad(loss_fn)(v)
            return v - lr_ * g

        for s in range(steps):
            negs = jnp.asarray(
                rng.choice(V, size=(len(idxs), self.negative),
                           p=self._unigram_table), jnp.int32)
            v = infer_step(v, negs, jnp.float32(lr * (1 - s / steps)))
        return np.asarray(v)

    def similarity_to_label(self, tokens: List[str], label: str) -> float:
        d = self.get_doc_vector(label)
        if d is None:
            return float("nan")       # matches similarity() on unknowns
        v = self.infer_vector(tokens)
        denom = np.linalg.norm(v) * np.linalg.norm(d)
        return float(v @ d / denom) if denom else 0.0
