from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, NGramTokenizerFactory, STOP_WORDS,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor, Huffman
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, SequenceVectors
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove

__all__ = ["DefaultTokenizerFactory", "NGramTokenizerFactory", "STOP_WORDS",
           "VocabCache", "VocabConstructor", "Huffman", "Word2Vec",
           "SequenceVectors", "ParagraphVectors", "Glove"]
