"""Lattice + Viterbi CJK segmentation — the Kuromoji/ansj architecture.

The reference bundles two full morphological analyzers: Kuromoji for
Japanese (deeplearning4j-nlp-japanese/src/main/java/com/atilika/
kuromoji/viterbi/ViterbiBuilder.java builds the lattice,
ViterbiSearcher.java walks it) and ansj for Chinese
(deeplearning4j-nlp-chinese/src/main/java/org/ansj/). Both resolve
segmentation AMBIGUITY the same way: every dictionary word that occurs
at every position becomes a lattice node; each node carries a word
cost (from corpus frequency) and adjacent nodes a connection cost; the
minimum-cost path through the lattice is the segmentation. Greedy
forward-maximum-matching (tokenization.CJKTokenizerFactory) cannot do
this — at 研究生命起源 it grabs the longest match 研究生 and is stuck
with the wrong 研究生|命|起源; the lattice compares whole-path costs
and recovers 研究|生命|起源.

This module is that architecture, TPU-framework-sized:

- :class:`LatticeDictionary` — words with costs (built from counts:
  cost = -log p, the unigram view of Kuromoji's word cost column) and
  an optional tag-pair connection matrix (the connection-cost matrix);
- :class:`ViterbiSegmenter` — lattice construction + min-cost dynamic
  program + backtrack, with Kuromoji-style unknown-word handling:
  out-of-dictionary characters group by character class (kanji run,
  katakana run, ...) with a length-scaled penalty, so unseen names
  stay whole instead of shattering into characters;
- :class:`LatticeCJKTokenizerFactory` — TokenizerFactory SPI plug-in:
  CJK runs go through the lattice, embedded Latin through the default
  tokenizer (same contract as CJKTokenizerFactory).
"""

from __future__ import annotations

import gzip
import math
import os
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from deeplearning4j_tpu.nlp.tokenization import (DefaultTokenizerFactory,
                                                 Tokenizer, _is_cjk)

__all__ = ["LatticeDictionary", "ViterbiSegmenter",
           "LatticeCJKTokenizerFactory", "small_cjk_dictionary",
           "chinese_dictionary", "japanese_dictionary",
           "korean_dictionary", "compile_dictionary"]

# ---------------------------------------------------------------------------
# Dictionary file format (the Kuromoji TSV → binary pipeline analog;
# reference compiles feature TSVs via DictionaryField.java /
# kuromoji-compile into binary dictionaries):
#
#   # comment
#   word<TAB>count<TAB>tag          entries (tag optional, default *)
#   @conn<TAB>left<TAB>right<TAB>cost   tag-pair connection costs
#
# Counts become word costs via -log(count/total) at load. `.tsv` and
# `.tsv.gz` are the source format; `compile_dictionary()` bakes the
# normalized costs into a `.npz` that loads without re-parsing — the
# binary-dictionary analog. Two non-toy dictionaries ship with the
# package (`nlp/data/`): zh_core (~65k entries derived from jieba's
# MIT-licensed frequency dictionary — tools/build_zh_dictionary.py)
# and ja_core (~560 curated morphemes: the closed-class particles and
# auxiliaries that drive Japanese segmentation, plus common content
# words and a tag-pair connection matrix).
# ---------------------------------------------------------------------------


class LatticeDictionary:
    """Word → (cost, tag). Costs are -log relative frequency when
    built via :meth:`from_counts` (Kuromoji stores corpus-derived
    costs in its dictionary binary; same quantity, readable form).
    ``connections`` maps (left_tag, right_tag) → cost, defaulting 0
    (the full analyzers learn a dense matrix; the hook is the
    architecture, the default keeps small dictionaries usable)."""

    def __init__(self, entries: Mapping[str, float] | None = None,
                 tags: Optional[Mapping[str, str]] = None,
                 connections: Optional[Mapping[Tuple[str, str],
                                               float]] = None):
        self._cost: Dict[str, float] = dict(entries or {})
        self._tag: Dict[str, str] = dict(tags or {})
        self._conn: Dict[Tuple[str, str], float] = dict(connections
                                                        or {})
        self._max_len = max((len(w) for w in self._cost), default=1)

    @classmethod
    def from_counts(cls, counts: Mapping[str, float], **kw):
        total = float(sum(counts.values())) or 1.0
        return cls({w: -math.log(c / total)
                    for w, c in counts.items() if c > 0}, **kw)

    @classmethod
    def from_tsv(cls, path: str) -> "LatticeDictionary":
        """Load the TSV source format (module docstring above);
        transparently handles ``.gz``."""
        counts: Dict[str, float] = {}
        tags: Dict[str, str] = {}
        conns: Dict[Tuple[str, str], float] = {}
        op = gzip.open if str(path).endswith(".gz") else open
        with op(path, "rt", encoding="utf-8") as f:
            for ln, line in enumerate(f, 1):
                line = line.rstrip("\r\n")   # tolerate CRLF-authored
                #                              files: '\r' in the last
                #                              field would corrupt tags
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t")
                if parts[0] == "@conn":
                    if len(parts) != 4:
                        raise ValueError(
                            f"{path}:{ln}: @conn needs left, right, "
                            f"cost — got {line!r}")
                    try:
                        conns[(parts[1], parts[2])] = float(parts[3])
                    except ValueError:
                        raise ValueError(f"{path}:{ln}: bad @conn "
                                         f"cost {parts[3]!r}") from None
                    continue
                if len(parts) < 2:
                    raise ValueError(f"{path}:{ln}: expected "
                                     f"word<TAB>count — got {line!r}")
                word = parts[0]
                try:
                    count = float(parts[1])
                except ValueError:
                    raise ValueError(f"{path}:{ln}: bad count "
                                     f"{parts[1]!r} for {word!r}") \
                        from None
                counts[word] = counts.get(word, 0.0) + count
                if len(parts) > 2 and parts[2] != "*":
                    tags[word] = parts[2]
        return cls.from_counts(counts, tags=tags, connections=conns)

    @classmethod
    def load(cls, path: str) -> "LatticeDictionary":
        """Dispatch on extension: ``.npz`` compiled, else TSV."""
        if str(path).endswith(".npz"):
            import numpy as np
            z = np.load(path, allow_pickle=False)
            words = [str(w) for w in z["words"]]
            costs = z["costs"]
            tags = {str(w): str(t)
                    for w, t in zip(z["tag_words"], z["tag_values"])}
            conns = {(str(l), str(r)): float(c)
                     for l, r, c in zip(z["conn_left"], z["conn_right"],
                                        z["conn_cost"])}
            return cls(dict(zip(words, costs.tolist())), tags=tags,
                       connections=conns)
        return cls.from_tsv(path)

    def save_compiled(self, path: str) -> str:
        """Bake into the `.npz` compiled form (normalized costs, no
        re-parse at load) — the binary-dictionary analog of
        kuromoji-compile."""
        import numpy as np
        words = sorted(self._cost)
        np.savez_compressed(
            path,
            words=np.array(words),
            costs=np.array([self._cost[w] for w in words], np.float64),
            tag_words=np.array(sorted(self._tag)),
            tag_values=np.array([self._tag[w]
                                 for w in sorted(self._tag)]),
            conn_left=np.array([k[0] for k in sorted(self._conn)]),
            conn_right=np.array([k[1] for k in sorted(self._conn)]),
            conn_cost=np.array([self._conn[k]
                                for k in sorted(self._conn)],
                               np.float64))
        return path if str(path).endswith(".npz") else path + ".npz"

    @property
    def max_len(self) -> int:
        return self._max_len

    def __contains__(self, word: str) -> bool:
        return word in self._cost

    def words(self):
        return self._cost.keys()

    def cost(self, word: str) -> float:
        return self._cost[word]

    def tag(self, word: str) -> str:
        return self._tag.get(word, "*")

    def connection(self, left_tag: str, right_tag: str) -> float:
        return self._conn.get((left_tag, right_tag), 0.0)

    def add(self, word: str, cost: float, tag: str = "*"):
        self._cost[word] = cost
        if tag != "*":
            self._tag[word] = tag
        self._max_len = max(self._max_len, len(word))
        return self


# character classes whose unknown-word candidates are generated even
# where dictionary words start (Kuromoji unknown invoke=1) — scripts
# where unseen stems fuse with known attachments
_ALWAYS_INVOKE = frozenset({"hangul", "katakana"})
_UNK_MAX_LEN = 12          # bound on invoke-always candidate length


def _char_class(ch: str) -> str:
    cp = ord(ch)
    if 0x3040 <= cp <= 0x309F:
        return "hiragana"
    if 0x30A0 <= cp <= 0x30FF:
        return "katakana"
    if 0xAC00 <= cp <= 0xD7AF:
        return "hangul"
    return "kanji"


class _Node:
    __slots__ = ("start", "end", "word", "cost", "tag", "best",
                 "back")

    def __init__(self, start, end, word, cost, tag):
        self.start = start
        self.end = end
        self.word = word
        self.cost = cost
        self.tag = tag
        self.best = math.inf     # min path cost up to and incl. self
        self.back = None


class ViterbiSegmenter:
    """Min-cost path through the word lattice (ViterbiSearcher.java's
    forward pass + backtrack, over ViterbiBuilder.java's lattice).

    ``unknown_cost``: per-character penalty for out-of-dictionary
    runs. Higher than any real word cost, so dictionary words are
    preferred, but one grouped unknown beats N singletons."""

    def __init__(self, dictionary: LatticeDictionary, *,
                 unknown_cost: float = 12.0):
        self.dict = dictionary
        self.unknown_cost = unknown_cost

    def _lattice(self, text: str) -> List[List[_Node]]:
        n = len(text)
        ending: List[List[_Node]] = [[] for _ in range(n + 1)]
        starts_covered = [False] * n
        for i in range(n):
            for l in range(1, min(self.dict.max_len, n - i) + 1):
                w = text[i:i + l]
                if w in self.dict:
                    ending[i + l].append(_Node(
                        i, i + l, w, self.dict.cost(w),
                        self.dict.tag(w)))
                    starts_covered[i] = True
        # unknown-word nodes: group maximal same-class runs starting at
        # positions no dictionary word covers (Kuromoji's unknown-word
        # processing groups by character class)
        for i in range(n):
            if starts_covered[i]:
                # also add the single char as an escape hatch so a
                # mid-word dictionary gap can't disconnect the lattice
                ending[i + 1].append(_Node(i, i + 1, text[i],
                                           self.unknown_cost, "unk"))
                continue
            cls = _char_class(text[i])
            j = i + 1
            while (j < n and not starts_covered[j]
                   and _char_class(text[j]) == cls):
                j += 1
            # the run and every prefix (prefixes keep the DP connected
            # when a dictionary word begins mid-run)
            for end in range(i + 1, j + 1):
                ending[end].append(_Node(
                    i, end, text[i:end],
                    self.unknown_cost * (1.0 + 0.3 * (end - i - 1)),
                    "unk"))
        # invoke-always classes (Kuromoji's unknown-word policy
        # invoke=1 for KATAKANA; hangul added here): from every CLASS
        #-RUN start, emit the run and its prefixes even THROUGH
        # positions where dictionary words also start. Agglutinative
        # scripts need this: an unseen Korean stem like 블록체인 must
        # stay a candidate although the dictionary ending 인 starts
        # inside it — without these nodes the only path is 블록체|인.
        for i in range(n):
            cls = _char_class(text[i])
            if cls not in _ALWAYS_INVOKE:
                continue
            if i > 0 and _char_class(text[i - 1]) == cls:
                continue                  # only class-run starts
            j = i + 1
            while j < n and _char_class(text[j]) == cls:
                j += 1
            # for an uncovered start the loop above already emitted
            # prefixes up to the first covered position — only the
            # spans BEYOND that truncation point are new
            first = i + 1
            if not starts_covered[i]:
                j1 = i + 1
                while j1 < n and not starts_covered[j1] \
                        and _char_class(text[j1]) == cls:
                    j1 += 1
                first = j1 + 1
            for end in range(first, min(j, i + _UNK_MAX_LEN) + 1):
                ending[end].append(_Node(
                    i, end, text[i:end],
                    self.unknown_cost * (1.0 + 0.3 * (end - i - 1)),
                    "unk"))
        return ending

    def segment(self, text: str) -> List[str]:
        if not text:
            return []
        n = len(text)
        ending = self._lattice(text)
        # forward DP over node ends; virtual BOS has cost 0 / tag *
        best_at: List[List[_Node]] = [[] for _ in range(n + 1)]
        for end in range(1, n + 1):
            for node in ending[end]:
                if node.start == 0:
                    node.best = node.cost
                    node.back = None
                else:
                    for prev in best_at[node.start]:
                        c = (prev.best + node.cost
                             + self.dict.connection(prev.tag, node.tag))
                        if c < node.best:
                            node.best = c
                            node.back = prev
                if node.best < math.inf:
                    best_at[end].append(node)
        tail = min(best_at[n], key=lambda nd: nd.best, default=None)
        if tail is None:                 # disconnected (shouldn't happen)
            return list(text)
        out: List[str] = []
        node = tail
        while node is not None:
            out.append(node.word)
            node = node.back
        return out[::-1]


def compile_dictionary(tsv_path: str, out_path: str) -> str:
    """TSV source → compiled ``.npz`` (counts normalized to costs;
    the kuromoji-compile analog)."""
    return LatticeDictionary.from_tsv(tsv_path).save_compiled(out_path)


_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "data")
_bundled_cache: Dict[str, LatticeDictionary] = {}


def _bundled(name: str) -> LatticeDictionary:
    if name not in _bundled_cache:
        _bundled_cache[name] = LatticeDictionary.from_tsv(
            os.path.join(_DATA_DIR, f"{name}.tsv.gz"))
    d = _bundled_cache[name]
    # hand out a COPY: callers may .add() custom terms, and a shared
    # singleton would leak those into every later default factory
    return LatticeDictionary(d._cost, tags=d._tag, connections=d._conn)


def chinese_dictionary() -> LatticeDictionary:
    """The bundled ~65k-entry Chinese dictionary (derived from jieba's
    MIT-licensed frequency list; tools/build_zh_dictionary.py) — the
    ansj-language-pack analog: real text segments out of the box."""
    return _bundled("zh_core")


def japanese_dictionary() -> LatticeDictionary:
    """The bundled Japanese core dictionary (~560 curated morphemes:
    closed-class particles/auxiliaries + common content words + a
    tag-pair connection matrix) — the Kuromoji-language-pack analog,
    relying on character-class unknown grouping for open-class OOV."""
    return _bundled("ja_core")


def korean_dictionary() -> LatticeDictionary:
    """The bundled Korean core dictionary (~900 curated morphemes:
    josa particles + verb/adjective endings + common content words +
    a tag-pair connection matrix — tools/build_ko_dictionary.py).
    Korean eojeol split stem|josa / stem|ending; an out-of-dictionary
    stem groups as one hangul unknown run that ends where a known
    attachment begins (the reference wraps an external analyzer for
    this, deeplearning4j-nlp-korean/.../KoreanTokenizer.java:24-40 —
    here it is the same lattice that serves zh/ja)."""
    return _bundled("ko_core")


def small_cjk_dictionary() -> LatticeDictionary:
    """A small bundled dictionary (counts → costs) exercising the
    classic segmentation ambiguities. A real deployment loads a corpus
    dictionary through LatticeDictionary.from_counts; bundling a
    curated core mirrors the reference shipping ansj/Kuromoji dicts
    inside the language-pack jars."""
    counts = {
        # 研究生命起源: correct 研究|生命|起源, FMM says 研究生|命|起源
        "研究": 5000, "生命": 4000, "起源": 1500, "研究生": 600,
        "命": 800, "生": 900,
        # 北京大学生前来应聘: correct 北京|大学生|前来|应聘
        "北京": 8000, "大学生": 2000, "大学": 6000, "北京大学": 700,
        "生前": 300, "前来": 1200, "应聘": 900, "来": 5000,
        # common particles / words for Japanese examples
        "東京": 7000, "東京都": 2500, "都": 1000, "京都": 3000,
        "すもも": 200, "もも": 900, "も": 8000, "の": 20000,
        "うち": 1500,
    }
    return LatticeDictionary.from_counts(counts)


class LatticeCJKTokenizerFactory:
    """TokenizerFactory SPI plug-in: Viterbi-lattice segmentation for
    CJK runs (the Kuromoji-class replacement for the greedy
    CJKTokenizerFactory), DefaultTokenizerFactory for Latin text.

    ``dictionary``: a LatticeDictionary, a path to a ``.tsv``/
    ``.tsv.gz``/compiled ``.npz`` dictionary file, or a bundled
    language pack name (``"zh"`` — default — / ``"ja"`` / ``"ko"``).
    Out of the box this segments real Chinese with the 65k-entry
    bundled dictionary (reference parity: the ansj/Kuromoji packs
    ship inside the language-pack jars)."""

    def __init__(self, dictionary=None, *, unknown_cost: float = 12.0):
        if dictionary is None or dictionary == "zh":
            dictionary = chinese_dictionary()
        elif dictionary == "ja":
            dictionary = japanese_dictionary()
        elif dictionary == "ko":
            dictionary = korean_dictionary()
        elif isinstance(dictionary, (str, os.PathLike)):
            dictionary = LatticeDictionary.load(dictionary)
        self.segmenter = ViterbiSegmenter(dictionary,
                                          unknown_cost=unknown_cost)
        self._latin = DefaultTokenizerFactory()
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        latin: List[str] = []
        run: List[str] = []

        def flush_latin():
            if latin:
                tokens.extend(self._latin.create(
                    "".join(latin)).get_tokens())
                latin.clear()

        def flush_run():
            if run:
                tokens.extend(self.segmenter.segment("".join(run)))
                run.clear()

        for ch in text:
            if _is_cjk(ch):
                flush_latin()
                run.append(ch)
            else:
                flush_run()
                latin.append(ch)
        flush_latin()
        flush_run()
        return Tokenizer(tokens, self._pre)
