"""Tokenization: TokenizerFactory SPI + preprocessors + stopwords.

Mirrors deeplearning4j-nlp's text layer (TokenizerFactory SPI,
DefaultTokenizerFactory, NGramTokenizerFactory,
CommonPreprocessor/EndingPreProcessor, stopwords list). Language packs
(ansj Chinese / Kuromoji Japanese bundles) are out of scope — the SPI
accepts any callable tokenizer, which is where those plug in.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional

__all__ = ["Tokenizer", "DefaultTokenizerFactory",
           "NGramTokenizerFactory", "CommonPreprocessor", "STOP_WORDS",
           "SentenceIterator", "ListSentenceIterator",
           "FileSentenceIterator"]

# the reference's stopwords resource (stopwords file in
# deeplearning4j-nlp resources), trimmed to the common core
STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it
no not of on or such that the their then there these they this to was will
with""".split())


class CommonPreprocessor:
    """Lowercase + strip punctuation (CommonPreprocessor.java)."""

    _punct = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._punct.sub("", token.lower())


class Tokenizer:
    def __init__(self, tokens: List[str], preprocessor=None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class DefaultTokenizerFactory:
    """Whitespace/word tokenizer (DefaultTokenizerFactory.java)."""

    _word = re.compile(r"\S+")

    def __init__(self):
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._word.findall(text), self._pre)


class NGramTokenizerFactory:
    """Word n-grams (NGramTokenizerFactory.java)."""

    def __init__(self, n_min: int, n_max: int):
        self.n_min = n_min
        self.n_max = n_max
        self._base = DefaultTokenizerFactory()

    def set_token_pre_processor(self, pre):
        self._base.set_token_pre_processor(pre)
        return self

    def create(self, text: str) -> Tokenizer:
        words = self._base.create(text).get_tokens()
        grams = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i:i + n]))
        return Tokenizer(grams)


class SentenceIterator:
    """(sentenceiterator SPI)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class ListSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)

    def __iter__(self):
        return iter(self.sentences)


class FileSentenceIterator(SentenceIterator):
    """One sentence per line (LineSentenceIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line
