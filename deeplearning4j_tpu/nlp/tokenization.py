"""Tokenization: TokenizerFactory SPI + preprocessors + stopwords.

Mirrors deeplearning4j-nlp's text layer (TokenizerFactory SPI,
DefaultTokenizerFactory, NGramTokenizerFactory,
CommonPreprocessor/EndingPreProcessor, stopwords list).

Language packs: the reference bundles full segmenter source trees
(ansj under deeplearning4j-nlp-chinese/src/main/java/org/ansj/,
Kuromoji under -japanese). Porting those dictionaries is out of scope,
but the SPI is proven by a REAL non-whitespace tokenizer:
:class:`CJKTokenizerFactory` segments CJK runs by forward maximum
matching against a user dictionary (the algorithmic core of ansj-style
segmenters) with per-character fallback, and handles mixed CJK/Latin
text. Any external segmenter plugs in the same way (create(text) ->
Tokenizer).
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional

__all__ = ["Tokenizer", "DefaultTokenizerFactory",
           "NGramTokenizerFactory", "CJKTokenizerFactory",
           "CommonPreprocessor", "EndingPreProcessor", "STOP_WORDS",
           "SentenceIterator", "ListSentenceIterator",
           "FileSentenceIterator"]

# the reference's stopwords resource (stopwords file in
# deeplearning4j-nlp resources), trimmed to the common core
STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it
no not of on or such that the their then there these they this to was will
with""".split())


class EndingPreProcessor:
    """Strips common English suffixes (text/tokenization/
    tokenizerfactory EndingPreProcessor: s/ed/ing/ly/.)."""

    _SUFFIXES = ("ing", "ed", "ly", "s", ".")

    def pre_process(self, token: str) -> str:
        for suf in self._SUFFIXES:
            if token.endswith(suf) and len(token) > len(suf) + 1:
                return token[:-len(suf)]
        return token


class CommonPreprocessor:
    """Lowercase + strip punctuation (CommonPreprocessor.java)."""

    _punct = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._punct.sub("", token.lower())


class Tokenizer:
    def __init__(self, tokens: List[str], preprocessor=None):
        self._tokens = tokens
        self._pre = preprocessor

    def get_tokens(self) -> List[str]:
        if self._pre is None:
            return list(self._tokens)
        out = []
        for t in self._tokens:
            t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out


class DefaultTokenizerFactory:
    """Whitespace/word tokenizer (DefaultTokenizerFactory.java)."""

    _word = re.compile(r"\S+")

    def __init__(self):
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._word.findall(text), self._pre)


class NGramTokenizerFactory:
    """Word n-grams (NGramTokenizerFactory.java)."""

    def __init__(self, n_min: int, n_max: int):
        self.n_min = n_min
        self.n_max = n_max
        self._base = DefaultTokenizerFactory()

    def set_token_pre_processor(self, pre):
        self._base.set_token_pre_processor(pre)
        return self

    def create(self, text: str) -> Tokenizer:
        words = self._base.create(text).get_tokens()
        grams = []
        for n in range(self.n_min, self.n_max + 1):
            for i in range(len(words) - n + 1):
                grams.append(" ".join(words[i:i + n]))
        return Tokenizer(grams)


_CJK_RANGES = (
    (0x4E00, 0x9FFF),     # CJK Unified Ideographs
    (0x3400, 0x4DBF),     # CJK Extension A
    (0x3040, 0x30FF),     # Hiragana + Katakana
    (0xAC00, 0xD7AF),     # Hangul syllables
    (0xF900, 0xFAFF),     # CJK Compatibility Ideographs
)


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return any(lo <= cp <= hi for lo, hi in _CJK_RANGES)


class CJKTokenizerFactory:
    """Dictionary-driven CJK segmentation — the plug-in proving the
    TokenizerFactory SPI carries real language packs (reference
    deeplearning4j-nlp-chinese bundles ansj; -japanese bundles
    Kuromoji). Forward maximum matching over CJK runs (the greedy
    longest-match core ansj-style segmenters build on), one-character
    fallback for out-of-dictionary text, whitespace/regex tokenization
    for embedded Latin runs.

    ``dictionary``: iterable of multi-character CJK words. Without one,
    CJK text tokenizes per character (the standard no-resource
    baseline).
    """

    def __init__(self, dictionary: Optional[Iterable[str]] = None):
        self._dict = set(dictionary or ())
        self._max_len = max((len(w) for w in self._dict), default=1)
        self._latin = DefaultTokenizerFactory()
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre
        return self

    def add_words(self, *words: str):
        self._dict.update(words)
        self._max_len = max((len(w) for w in self._dict), default=1)
        return self

    def _segment_cjk(self, run: str) -> List[str]:
        out: List[str] = []
        i = 0
        n = len(run)
        while i < n:
            matched = None
            for l in range(min(self._max_len, n - i), 1, -1):
                if run[i:i + l] in self._dict:
                    matched = run[i:i + l]
                    break
            if matched is None:
                matched = run[i]          # single-character fallback
            out.append(matched)
            i += len(matched)
        return out

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        run = []
        for ch in text:
            if _is_cjk(ch):
                run.append(ch)
            else:
                if run:
                    tokens.extend(self._segment_cjk("".join(run)))
                    run = []
                tokens.append(ch)
        if run:
            tokens.extend(self._segment_cjk("".join(run)))
        # re-tokenize the non-CJK fragments with the Latin tokenizer
        final: List[str] = []
        latin_buf = []
        for t in tokens:
            if len(t) == 1 and not _is_cjk(t):
                latin_buf.append(t)
            else:
                if latin_buf:
                    final.extend(self._latin.create(
                        "".join(latin_buf)).get_tokens())
                    latin_buf = []
                final.append(t)
        if latin_buf:
            final.extend(self._latin.create(
                "".join(latin_buf)).get_tokens())
        return Tokenizer(final, self._pre)


class SentenceIterator:
    """(sentenceiterator SPI)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class ListSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)

    def __iter__(self):
        return iter(self.sentences)


class FileSentenceIterator(SentenceIterator):
    """One sentence per line (LineSentenceIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line
