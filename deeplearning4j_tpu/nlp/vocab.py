"""Vocabulary construction + Huffman coding.

Mirrors models/word2vec/wordstore/VocabConstructor.java:167
(buildJointVocabulary: count, prune by minWordFrequency) +
AbstractCache and models/word2vec/Huffman.java (binary Huffman tree
over word frequencies, producing per-word codes/paths for hierarchical
softmax).
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = ["VocabWord", "VocabCache", "VocabConstructor", "Huffman"]


class VocabWord:
    __slots__ = ("word", "count", "index", "codes", "points")

    def __init__(self, word: str, count: int = 0, index: int = -1):
        self.word = word
        self.count = count
        self.index = index
        self.codes: List[int] = []
        self.points: List[int] = []


class VocabCache:
    """(AbstractCache.java): index ↔ word ↔ frequency."""

    def __init__(self):
        self.words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_count = 0

    def add(self, vw: VocabWord):
        vw.index = len(self.words)
        self.words.append(vw)
        self._by_word[vw.word] = vw

    def __len__(self):
        return len(self.words)

    def __contains__(self, w: str):
        return w in self._by_word

    def word_for(self, w: str) -> Optional[VocabWord]:
        return self._by_word.get(w)

    def index_of(self, w: str) -> int:
        vw = self._by_word.get(w)
        return -1 if vw is None else vw.index

    def word_at(self, i: int) -> str:
        return self.words[i].word

    def frequencies(self) -> np.ndarray:
        return np.array([w.count for w in self.words], np.float64)


class VocabConstructor:
    """(VocabConstructor.java:31)."""

    def __init__(self, min_word_frequency: int = 5,
                 stop_words: Iterable[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.stop_words = set(stop_words)

    def build_joint_vocabulary(self, token_sequences) -> VocabCache:
        counts: Dict[str, int] = {}
        total = 0
        for seq in token_sequences:
            for tok in seq:
                if tok in self.stop_words:
                    continue
                counts[tok] = counts.get(tok, 0) + 1
                total += 1
        cache = VocabCache()
        # descending frequency, ties alphabetical: stable indexing
        for word, c in sorted(counts.items(), key=lambda kv: (-kv[1],
                                                              kv[0])):
            if c >= self.min_word_frequency:
                cache.add(VocabWord(word, c))
        cache.total_count = total
        return cache


class Huffman:
    """(models/word2vec/Huffman.java): assigns binary codes + inner-node
    paths to each vocab word for hierarchical softmax. Inner nodes are
    numbered 0..V-2; word w's ``points`` are the inner nodes on its
    root→leaf path, ``codes`` the branch bits."""

    MAX_CODE_LENGTH = 40

    def __init__(self, cache: VocabCache):
        self.cache = cache
        self.build()

    def build(self):
        V = len(self.cache)
        if V == 0:
            return
        # heap of (count, tiebreak, node_id); leaves 0..V-1, inner V..2V-2
        heap = [(w.count, i, i) for i, w in enumerate(self.cache.words)]
        heapq.heapify(heap)
        parent = {}
        code_of = {}
        next_id = V
        while len(heap) > 1:
            c1, _, n1 = heapq.heappop(heap)
            c2, _, n2 = heapq.heappop(heap)
            parent[n1] = next_id
            parent[n2] = next_id
            code_of[n1] = 0
            code_of[n2] = 1
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = heap[0][2]
        for i, w in enumerate(self.cache.words):
            codes, points = [], []
            node = i
            while node != root:
                codes.append(code_of[node])
                node = parent[node]
                points.append(node - V)    # inner-node index 0..V-2
            codes.reverse()
            points.reverse()
            w.codes = codes[:self.MAX_CODE_LENGTH]
            w.points = points[:self.MAX_CODE_LENGTH]

    def padded_arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(points, codes, mask) as (V, L) int arrays padded to the max
        path length — ready for a batched hierarchical-softmax kernel."""
        V = len(self.cache)
        L = max((len(w.codes) for w in self.cache.words), default=1)
        points = np.zeros((V, L), np.int32)
        codes = np.zeros((V, L), np.float32)
        mask = np.zeros((V, L), np.float32)
        for i, w in enumerate(self.cache.words):
            n = len(w.codes)
            points[i, :n] = w.points
            codes[i, :n] = w.codes
            mask[i, :n] = 1.0
        return points, codes, mask
