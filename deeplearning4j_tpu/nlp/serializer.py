"""Word vector serialization + bag-of-words/TF-IDF vectorizers.

Mirrors models/embeddings/loader/WordVectorSerializer.java (classic
word2vec text format: header 'V D', then 'word v1 v2 ...') and
bagofwords/vectorizer (BagOfWordsVectorizer, TfidfVectorizer).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord

__all__ = ["write_word_vectors", "read_word_vectors",
           "BagOfWordsVectorizer", "TfidfVectorizer"]


def write_word_vectors(model, path: str) -> None:
    """word2vec .vec text format."""
    V, D = model.syn0.shape
    with open(path, "w") as f:
        f.write(f"{V} {D}\n")
        for i in range(V):
            word = model.vocab.word_at(i)
            vec = " ".join(f"{x:.6f}" for x in model.syn0[i])
            f.write(f"{word} {vec}\n")


def read_word_vectors(path: str):
    """Returns (VocabCache, np.ndarray) from .vec text format."""
    with open(path) as f:
        header = f.readline().split()
        V, D = int(header[0]), int(header[1])
        cache = VocabCache()
        vecs = np.zeros((V, D), np.float32)
        for i in range(V):
            parts = f.readline().rstrip("\n").split(" ")
            cache.add(VocabWord(parts[0], 1))
            vecs[i] = [float(x) for x in parts[1:D + 1]]
    return cache, vecs


class BagOfWordsVectorizer:
    """(bagofwords/vectorizer/BagOfWordsVectorizer.java)."""

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency
        self.vocab: Optional[VocabCache] = None

    def fit(self, documents: Iterable[List[str]]):
        from deeplearning4j_tpu.nlp.vocab import VocabConstructor
        self.vocab = VocabConstructor(
            self.min_word_frequency).build_joint_vocabulary(documents)
        return self

    def transform(self, document: List[str]) -> np.ndarray:
        v = np.zeros(len(self.vocab), np.float32)
        for tok in document:
            i = self.vocab.index_of(tok)
            if i >= 0:
                v[i] += 1.0
        return v

    def fit_transform(self, documents: List[List[str]]) -> np.ndarray:
        self.fit(documents)
        return np.stack([self.transform(d) for d in documents])


class TfidfVectorizer(BagOfWordsVectorizer):
    """(bagofwords/vectorizer/TfidfVectorizer.java): tf * log(N/df)."""

    def __init__(self, min_word_frequency: int = 1):
        super().__init__(min_word_frequency)
        self.idf: Optional[np.ndarray] = None

    def fit(self, documents: Iterable[List[str]]):
        documents = [list(d) for d in documents]
        super().fit(documents)
        df = np.zeros(len(self.vocab), np.float64)
        for d in documents:
            for i in {self.vocab.index_of(t) for t in d}:
                if i >= 0:
                    df[i] += 1
        n = len(documents)
        self.idf = np.log(n / np.maximum(df, 1.0)).astype(np.float32)
        return self

    def transform(self, document: List[str]) -> np.ndarray:
        tf = super().transform(document)
        total = max(tf.sum(), 1.0)
        return (tf / total) * self.idf
