"""Graph embeddings: DeepWalk + random walks.

Mirrors deeplearning4j-graph (graph/models/deepwalk/DeepWalk.java:31,95
fit(IGraph, walkLength); graph/iterator/RandomWalkIterator;
GraphHuffman): random walks over an adjacency structure feed the
SequenceVectors skip-gram trainer (hierarchical softmax available via
hs=True — the reference's GraphHuffman path).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.word2vec import SequenceVectors

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["Graph", "DeepWalk"]


class Graph:
    """Minimal IGraph (deeplearning4j-graph api/IGraph semantics):
    vertices 0..n-1, directed or undirected edges."""

    def __init__(self, n_vertices: int, undirected: bool = True):
        self.n = n_vertices
        self.undirected = undirected
        self.adj: List[List[int]] = [[] for _ in range(n_vertices)]

    def add_edge(self, a: int, b: int):
        self.adj[a].append(b)
        if self.undirected:
            self.adj[b].append(a)

    def degree(self, v: int) -> int:
        return len(self.adj[v])


class DeepWalk:
    """(DeepWalk.java): uniform random walks → skip-gram."""

    def __init__(self, *, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 learning_rate: float = 0.025, negative: int = 5,
                 hs: bool = False, epochs: int = 1, seed: int = 123,
                 batch_size: int = 256):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.seed = seed
        self._sv = SequenceVectors(
            layer_size=vector_size, window=window_size,
            negative=negative, hs=hs, learning_rate=learning_rate,
            min_word_frequency=1, subsampling=0.0, epochs=epochs,
            seed=seed, batch_size=batch_size)

    def _walks(self, graph: Graph, rng) -> List[List[str]]:
        walks = []
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(graph.n):
                walk = [int(start)]
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    nbrs = graph.adj[cur]
                    if not nbrs:
                        break
                    cur = int(nbrs[rng.integers(0, len(nbrs))])
                    walk.append(cur)
                walks.append([str(v) for v in walk])
        return walks

    def fit(self, graph: Graph) -> "DeepWalk":
        rng = np.random.default_rng(self.seed)
        walks = self._walks(graph, rng)
        logger.info("DeepWalk: %d walks over %d vertices", len(walks),
                    graph.n)
        self._sv.fit(walks)
        return self

    def get_vertex_vector(self, v: int) -> Optional[np.ndarray]:
        return self._sv.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.similarity(str(a), str(b))

    def verts_nearest(self, v: int, n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.words_nearest(str(v), n)]


class Node2Vec(DeepWalk):
    """node2vec (Grover & Leskovec): 2nd-order biased random walks with
    return parameter p and in-out parameter q over the DeepWalk trainer
    (the reference exposes Node2Vec atop SequenceVectors too)."""

    def __init__(self, *, p: float = 1.0, q: float = 1.0, **kw):
        super().__init__(**kw)
        self.p = p
        self.q = q

    def _walks(self, graph: Graph, rng) -> List[List[str]]:
        walks = []
        adj_sets = [set(a) for a in graph.adj]
        for _ in range(self.walks_per_vertex):
            for start in rng.permutation(graph.n):
                walk = [int(start)]
                prev = None
                cur = int(start)
                for _ in range(self.walk_length - 1):
                    nbrs = graph.adj[cur]
                    if not nbrs:
                        break
                    if prev is None:
                        nxt = int(nbrs[rng.integers(0, len(nbrs))])
                    else:
                        w = np.empty(len(nbrs))
                        for i, x in enumerate(nbrs):
                            if x == prev:
                                w[i] = 1.0 / self.p      # return
                            elif x in adj_sets[prev]:
                                w[i] = 1.0               # distance 1
                            else:
                                w[i] = 1.0 / self.q      # explore
                        w /= w.sum()
                        nxt = int(nbrs[rng.choice(len(nbrs), p=w)])
                    walk.append(nxt)
                    prev, cur = cur, nxt
                walks.append([str(v) for v in walk])
        return walks
