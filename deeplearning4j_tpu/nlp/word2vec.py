"""Word2Vec / SequenceVectors: embedding training, TPU-style.

Mirrors models/sequencevectors/SequenceVectors.java:192 (fit →
buildVocab → train) with SkipGram/CBOW elements
(models/embeddings/learning/impl/elements/SkipGram.java, CBOW.java),
negative sampling and hierarchical softmax, lookup tables
(InMemoryLookupTable) and the Word2Vec builder facade
(models/word2vec/Word2Vec.java:621).

Design shift (the whole point of the rebuild): the reference trains
with N ``VectorCalculationsThread``s doing lock-free rank-1 updates on
shared syn0/syn1 (HOGWILD). On TPU that becomes ONE jitted step over a
*batch* of (center, context, negatives) pairs — embedding gathers, a
(B, K+1) dot-product block, sigmoid CE, and scatter-add gradients via
autodiff of ``jnp.take``. Deterministic given the seed, and the MXU
does the work.
"""

from __future__ import annotations

import functools
import logging
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nlp.tokenization import (DefaultTokenizerFactory,
                                                 SentenceIterator)
from deeplearning4j_tpu.nlp.vocab import (Huffman, VocabCache,
                                          VocabConstructor)

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["SequenceVectors", "Word2Vec"]


def _clip_rows(g, max_norm: float = 5.0):
    """Per-row gradient clip: a batched step sums the updates of every
    occurrence of a word (unlike the reference's sequential HOGWILD
    rank-1 updates), so frequent rows in small vocabularies can get
    O(batch) gradients — clip keeps the effective per-step movement in
    the classic range."""
    n = jnp.linalg.norm(g, axis=-1, keepdims=True)
    return g * jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))


class SequenceVectors:
    """Generic embedding trainer over element sequences
    (SequenceVectors.java)."""

    def __init__(self, *, layer_size: int = 100, window: int = 5,
                 negative: int = 5, hs: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 min_word_frequency: int = 5, subsampling: float = 1e-3,
                 epochs: int = 1, batch_size: int = 512, seed: int = 123,
                 stop_words: Iterable[str] = (),
                 algorithm: str = "skipgram"):
        if algorithm not in ("skipgram", "cbow"):
            raise ValueError(f"Unknown algorithm '{algorithm}'")
        self.algorithm = algorithm
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.hs = hs
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.min_word_frequency = min_word_frequency
        self.subsampling = subsampling
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.stop_words = stop_words
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None
        self.syn1: Optional[np.ndarray] = None
        self._unigram_table: Optional[np.ndarray] = None
        self._hs_arrays = None

    # -------------------------------------------------------------- vocab
    def build_vocab(self, sequences: List[List[str]]):
        self.vocab = VocabConstructor(
            self.min_word_frequency,
            self.stop_words).build_joint_vocabulary(sequences)
        if len(self.vocab) == 0:
            raise ValueError("Empty vocabulary (check minWordFrequency)")
        rng = np.random.default_rng(self.seed)
        V, D = len(self.vocab), self.layer_size
        self.syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
        self.syn1 = np.zeros((V, D), np.float32)
        freqs = self.vocab.frequencies()
        # negative-sampling unigram distribution ^0.75 (word2vec classic)
        probs = freqs ** 0.75
        self._unigram_table = (probs / probs.sum()).astype(np.float64)
        if self.hs:
            self._hs_arrays = Huffman(self.vocab).padded_arrays()

    # ------------------------------------------------------------ training
    def _training_pairs(self, sequences, rng: np.random.Generator):
        """Yield (center, context) index pairs with dynamic window +
        frequency subsampling (word2vec semantics the reference keeps in
        SkipGram.learnSequence)."""
        vocab = self.vocab
        freqs = vocab.frequencies()
        total = max(freqs.sum(), 1.0)
        keep_prob = np.ones(len(vocab))
        if self.subsampling > 0:
            f = freqs / total
            keep_prob = np.minimum(
                1.0, (np.sqrt(f / self.subsampling) + 1)
                * self.subsampling / np.maximum(f, 1e-12))
        for seq in sequences:
            idxs = [vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0
                    and rng.random() < keep_prob[i]]
            n = len(idxs)
            for pos, center in enumerate(idxs):
                b = rng.integers(1, self.window + 1)
                for off in range(-b, b + 1):
                    if off == 0:
                        continue
                    j = pos + off
                    if 0 <= j < n:
                        yield center, idxs[j]

    def _make_ns_step(self):
        K = self.negative

        @jax.jit
        def step(syn0, syn1, centers, contexts, negatives, lr):
            def loss_fn(s0, s1):
                c = jnp.take(s0, centers, axis=0)            # (B,D)
                pos = jnp.take(s1, contexts, axis=0)         # (B,D)
                neg = jnp.take(s1, negatives, axis=0)        # (B,K,D)
                pos_score = jnp.sum(c * pos, axis=-1)        # (B,)
                neg_score = jnp.einsum("bd,bkd->bk", c, neg)
                # sigmoid CE: -log σ(pos) - Σ log σ(-neg); SUM over the
                # batch (not mean) so lr has classic per-pair semantics
                loss = (jnp.sum(jax.nn.softplus(-pos_score))
                        + jnp.sum(jax.nn.softplus(neg_score)))
                return loss
            loss, (g0, g1) = jax.value_and_grad(loss_fn, (0, 1))(syn0,
                                                                syn1)
            return (syn0 - lr * _clip_rows(g0),
                    syn1 - lr * _clip_rows(g1), loss)

        return step

    def _make_cbow_step(self):
        """CBOW (learning/impl/elements/CBOW.java): the mean of the
        context-word vectors predicts the center word, negative
        sampling on syn1. Contexts arrive as a fixed-width (B, 2W)
        index matrix with a validity mask."""

        if self.hs:
            points, codes, mask = self._hs_arrays
            points = jnp.asarray(points)
            codes = jnp.asarray(codes)
            hmask = jnp.asarray(mask)

        @jax.jit
        def step(syn0, syn1, contexts, ctx_mask, centers, negatives, lr):
            def loss_fn(s0, s1):
                ctx = jnp.take(s0, contexts, axis=0)         # (B,2W,D)
                denom = jnp.maximum(
                    jnp.sum(ctx_mask, axis=1, keepdims=True), 1.0)
                h = jnp.sum(ctx * ctx_mask[..., None], axis=1) / denom
                if self.hs:
                    # hierarchical softmax on the CENTER word's path
                    pts = jnp.take(points, centers, axis=0)
                    cds = jnp.take(codes, centers, axis=0)
                    msk = jnp.take(hmask, centers, axis=0)
                    node_vecs = jnp.take(s1, pts, axis=0)    # (B,L,D)
                    scores = jnp.einsum("bd,bld->bl", h, node_vecs)
                    per = jax.nn.softplus(scores) - cds * scores
                    return jnp.sum(per * msk)
                pos = jnp.take(s1, centers, axis=0)          # (B,D)
                neg = jnp.take(s1, negatives, axis=0)        # (B,K,D)
                pos_score = jnp.sum(h * pos, axis=-1)
                neg_score = jnp.einsum("bd,bkd->bk", h, neg)
                return (jnp.sum(jax.nn.softplus(-pos_score))
                        + jnp.sum(jax.nn.softplus(neg_score)))
            loss, (g0, g1) = jax.value_and_grad(loss_fn, (0, 1))(syn0,
                                                                syn1)
            return (syn0 - lr * _clip_rows(g0),
                    syn1 - lr * _clip_rows(g1), loss)

        return step

    def _cbow_batches(self, sequences, rng):
        """(contexts (B,2W), mask, centers) tuples. Applies the same
        frequency subsampling as the skip-gram path."""
        vocab = self.vocab
        W = self.window
        freqs = vocab.frequencies()
        total = max(freqs.sum(), 1.0)
        keep_prob = np.ones(len(vocab))
        if self.subsampling > 0:
            f = freqs / total
            keep_prob = np.minimum(
                1.0, (np.sqrt(f / self.subsampling) + 1)
                * self.subsampling / np.maximum(f, 1e-12))
        ctxs, masks, centers = [], [], []
        for seq in sequences:
            idxs = [vocab.index_of(t) for t in seq]
            idxs = [i for i in idxs if i >= 0
                    and rng.random() < keep_prob[i]]
            n = len(idxs)
            for pos, center in enumerate(idxs):
                row = np.zeros(2 * W, np.int32)
                m = np.zeros(2 * W, np.float32)
                j = 0
                for off in range(-W, W + 1):
                    if off == 0:
                        continue
                    k = pos + off
                    if 0 <= k < n:
                        row[j] = idxs[k]
                        m[j] = 1.0
                        j += 1
                if j:
                    ctxs.append(row)
                    masks.append(m)
                    centers.append(center)
        return (np.stack(ctxs) if ctxs else np.zeros((0, 2 * W), np.int32),
                np.stack(masks) if masks else np.zeros((0, 2 * W),
                                                       np.float32),
                np.asarray(centers, np.int32))

    def _fit_cbow(self, sequences):
        rng = np.random.default_rng(self.seed + 1)
        step = self._make_cbow_step()
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self.syn1)
        V = len(self.vocab)
        B = self.batch_size
        ctxs, masks, centers = self._cbow_batches(sequences, rng)
        n = len(centers)
        if n == 0:
            raise ValueError("No CBOW training examples")
        total_steps = max(1, n * self.epochs // B)
        step_i = 0
        for _ in range(self.epochs):
            order = rng.permutation(n)
            if n < B:
                order = np.resize(order, B)
            for s in range(0, len(order) - B + 1, B):
                sel = order[s:s + B]
                negs = rng.choice(V, size=(B, self.negative),
                                  p=self._unigram_table)
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1 - step_i / total_steps))
                syn0, syn1, loss = step(
                    syn0, syn1, jnp.asarray(ctxs[sel]),
                    jnp.asarray(masks[sel]), jnp.asarray(centers[sel]),
                    jnp.asarray(negs, jnp.int32), jnp.float32(lr))
                step_i += 1
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        return self

    def _make_hs_step(self):
        points, codes, mask = self._hs_arrays
        points = jnp.asarray(points)
        codes = jnp.asarray(codes)
        mask = jnp.asarray(mask)

        @jax.jit
        def step(syn0, syn1, centers, contexts, lr):
            def loss_fn(s0, s1):
                c = jnp.take(s0, centers, axis=0)            # (B,D)
                pts = jnp.take(points, contexts, axis=0)     # (B,L)
                cds = jnp.take(codes, contexts, axis=0)
                msk = jnp.take(mask, contexts, axis=0)
                node_vecs = jnp.take(s1, pts, axis=0)        # (B,L,D)
                scores = jnp.einsum("bd,bld->bl", c, node_vecs)
                # BCE against the Huffman code bits; SUM (per-pair lr)
                per = jax.nn.softplus(scores) - cds * scores
                return jnp.sum(per * msk)
            loss, (g0, g1) = jax.value_and_grad(loss_fn, (0, 1))(syn0,
                                                                syn1)
            return (syn0 - lr * _clip_rows(g0),
                    syn1 - lr * _clip_rows(g1), loss)

        return step

    def fit(self, sequences: List[List[str]], mesh=None):
        """Train. With ``mesh`` (a jax Mesh with a 'data' axis) the
        pair batches are sharded over the axis and embeddings stay
        replicated — XLA inserts the cross-device reduction for the
        scatter updates. This is the TPU-native replacement for the
        reference's Spark Word2Vec/TextPipeline data-parallel training
        (dl4j-spark-nlp/.../TextPipeline.java: word counting and
        training distributed over executors)."""
        if self.vocab is None:
            self.build_vocab(sequences)
        if self.algorithm == "cbow":
            return self._fit_cbow(sequences)
        rng = np.random.default_rng(self.seed + 1)
        step = self._make_hs_step() if self.hs else self._make_ns_step()
        syn0 = jnp.asarray(self.syn0)
        syn1 = jnp.asarray(self.syn1)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            ndata = mesh.shape["data"]
            if self.batch_size % ndata:
                raise ValueError(
                    f"batch_size {self.batch_size} not divisible by "
                    f"mesh data axis {ndata}")
            repl = NamedSharding(mesh, P())
            shard = NamedSharding(mesh, P("data"))
            syn0 = jax.device_put(syn0, repl)
            syn1 = jax.device_put(syn1, repl)

            def put(a):
                return jax.device_put(a, shard)
        else:
            def put(a):
                return a
        V = len(self.vocab)
        B = self.batch_size
        # total pair estimate for lr decay
        pairs = list(self._training_pairs(sequences, rng))
        total_steps = max(1, (len(pairs) * self.epochs) // B)
        step_i = 0
        last_loss = None
        for ep in range(self.epochs):
            if ep > 0:
                pairs = list(self._training_pairs(sequences, rng))
            if not pairs:
                continue
            order = rng.permutation(len(pairs))
            if len(pairs) < B:
                # tiny corpora: wrap-pad to one full batch so shapes
                # stay static for jit
                order = np.resize(order, B)
            for s in range(0, len(order) - B + 1, B):
                sel = order[s:s + B]
                centers = put(jnp.asarray([pairs[i][0] for i in sel],
                                          jnp.int32))
                contexts = put(jnp.asarray([pairs[i][1] for i in sel],
                                           jnp.int32))
                frac = step_i / total_steps
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1 - frac))
                if self.hs:
                    syn0, syn1, loss = step(syn0, syn1, centers,
                                            contexts, jnp.float32(lr))
                else:
                    negs = rng.choice(V, size=(len(sel), self.negative),
                                      p=self._unigram_table)
                    syn0, syn1, loss = step(
                        syn0, syn1, centers, contexts,
                        put(jnp.asarray(negs, jnp.int32)),
                        jnp.float32(lr))
                step_i += 1
                last_loss = loss
        self.syn0 = np.asarray(syn0)
        self.syn1 = np.asarray(syn1)
        if last_loss is not None:
            logger.info("SequenceVectors fit done: %d steps, loss %.4f",
                        step_i, float(last_loss))
        return self

    # ------------------------------------------------------------- queries
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.syn0[i]

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def _unit_syn0(self) -> np.ndarray:
        """Row-normalized vectors, cached (and invalidated when syn0's
        identity changes — training replaces the array). At 100k+
        vocab, normalizing per query was the scaling bottleneck."""
        cached = getattr(self, "_unit_cache", None)
        if cached is not None and cached[0] is self.syn0:
            return cached[1]
        norms = np.linalg.norm(self.syn0, axis=1, keepdims=True)
        unit = self.syn0 / np.maximum(norms, 1e-12)
        self._unit_cache = (self.syn0, unit)
        return unit

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        return self.words_nearest_batch([word], n=n)[0]

    def words_nearest_batch(self, words: List[str], n: int = 10,
                            chunk: int = 1024) -> List[List[str]]:
        """Top-n neighbors for MANY query words via chunked matmul +
        argpartition — the lookup-table-scale path (reference
        InMemoryLookupTable wordsNearest over 100k+ vocab). Memory is
        bounded at (chunk, V) regardless of query count."""
        unit = self._unit_syn0()
        out: List[List[str]] = []
        idxs, valid = [], []
        for w in words:
            i = self.vocab.index_of(w)
            idxs.append(i if i is not None and i >= 0 else 0)
            valid.append(i is not None and i >= 0)
        idxs = np.asarray(idxs)
        for lo in range(0, len(words), chunk):
            hi = min(lo + chunk, len(words))
            sims = unit[idxs[lo:hi]] @ unit.T          # (chunk, V)
            for r in range(hi - lo):
                if not valid[lo + r]:
                    out.append([])
                    continue
                sims[r, idxs[lo + r]] = -np.inf
                k = min(n, sims.shape[1] - 1)
                # argpartition: O(V) instead of O(V log V) full sort
                part = np.argpartition(-sims[r], k)[:k]
                top = part[np.argsort(-sims[r][part])]
                out.append([self.vocab.word_at(i) for i in top])
        return out


class Word2Vec(SequenceVectors):
    """User-facing builder facade (models/word2vec/Word2Vec.java)."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator: Optional[SentenceIterator] = None
            self._tokenizer = DefaultTokenizerFactory()

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window"] = n
            return self

        def negative_sample(self, n):
            self._kw["negative"] = n
            return self

        def use_hierarchic_softmax(self, b=True):
            self._kw["hs"] = b
            return self

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def sampling(self, s):
            self._kw["subsampling"] = s
            return self

        def batch_size(self, n):
            self._kw["batch_size"] = n
            return self

        def stop_words(self, sw):
            self._kw["stop_words"] = sw
            return self

        def elements_learning_algorithm(self, name: str):
            """'skipgram' | 'cbow' (reference
            elementsLearningAlgorithm(SkipGram/CBOW))."""
            self._kw["algorithm"] = name.lower()
            return self

        def iterate(self, it: SentenceIterator):
            self._iterator = it
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def build(self) -> "Word2Vec":
            w = Word2Vec(**self._kw)
            w._iterator = self._iterator
            w._tokenizer = self._tokenizer
            return w

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def __init__(self, **kw):
        super().__init__(**kw)
        self._iterator = None
        self._tokenizer = DefaultTokenizerFactory()

    def fit(self, sequences=None, mesh=None):
        if sequences is None:
            if self._iterator is None:
                raise ValueError("No sentence iterator configured")
            sequences = [self._tokenizer.create(s).get_tokens()
                         for s in self._iterator]
        return super().fit(sequences, mesh=mesh)
