from deeplearning4j_tpu.cli import main

main()
