"""Batched on-device text -> vector encoding over a Word2Vec vocab.

The query-side half of the retrieval subsystem: texts tokenize on the
host (the same ``DefaultTokenizerFactory`` SPI the Word2Vec trainer
uses), pack into fixed-shape ``(B, 2, L)`` id+mask tensors, and the
embedding itself — table lookup, masked mean-pool, optional unit
normalization — runs as ONE jitted op over the whole batch.

The packed tensor IS the serving wire format: a ``TextEmbedder``
registers in the ``ModelRegistry`` like any predict model (it exposes
``.output``), so ``/v1/embed`` resolves it through
``resolve_serving_model`` and batches it through the ordinary
``BatchScheduler`` — deadlines, tiers, chaos and all. Sequence
lengths pad to pow2 buckets (capped at ``max_tokens``), so the
compiled-executable count is O(log max_tokens · log max_batch), not
per-request.

Out-of-vocabulary tokens drop out of the mean (mask 0); an all-OOV or
empty text embeds to the zero vector, which cosine search scores
-inf-equivalently (zero dot against every unit row).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.retrieval.index import pow2_bucket

__all__ = ["TextEmbedder"]

# shortest padded token length: tiny queries share one compiled shape
_MIN_TOKENS = 8


@functools.partial(jax.jit, static_argnames=("normalize",))
def _mean_pool(table, packed, normalize):
    """packed (B, 2, L): row 0 token indices (float storage), row 1
    the validity mask. Returns (B, D) mean-pooled embeddings."""
    ids = packed[:, 0, :].astype(jnp.int32)
    mask = packed[:, 1, :]
    vecs = table[ids] * mask[..., None]
    denom = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    out = jnp.sum(vecs, axis=1) / denom
    if normalize:
        norm = jnp.linalg.norm(out, axis=1, keepdims=True)
        out = out / jnp.maximum(norm, 1e-12)
    return out


class TextEmbedder:
    """Mean-pooled word-vector encoder behind the predict-model shape.

    ``vocab`` is either a ``VocabCache`` (the Word2Vec family's) or a
    plain ``{token: row}`` dict; ``vectors`` the (V, D) embedding
    table those rows index. ``from_word2vec`` adapts a trained
    ``Word2Vec``/``ParagraphVectors`` instance directly.
    """

    def __init__(self, vocab, vectors,
                 normalize: bool = True,
                 max_tokens: int = 64,
                 tokenizer_factory=None):
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[0] < 1:
            raise ValueError(
                f"vectors must be a (V, D) table; got {vectors.shape}")
        if hasattr(vocab, "index_of"):
            self._index_of = vocab.index_of
            self._vocab_size = len(vocab)
        elif isinstance(vocab, dict):
            self._index_of = lambda tok: vocab.get(tok, -1)
            self._vocab_size = len(vocab)
        else:
            raise TypeError(
                "vocab must be a VocabCache-like (index_of) or a "
                f"token->row dict; got {type(vocab).__name__}")
        if self._vocab_size > vectors.shape[0]:
            raise ValueError(
                f"vocab has {self._vocab_size} entries but the table "
                f"only {vectors.shape[0]} rows")
        self.dim = int(vectors.shape[1])
        self.normalize = bool(normalize)
        self.max_tokens = int(max_tokens)
        if self.max_tokens < 1:
            raise ValueError("max_tokens must be positive")
        self._table = jnp.asarray(vectors)
        self._tokenizer = tokenizer_factory or DefaultTokenizerFactory()

    @classmethod
    def from_word2vec(cls, w2v, **kwargs) -> "TextEmbedder":
        """Adapt a trained SequenceVectors (Word2Vec /
        ParagraphVectors): its vocab + syn0 + tokenizer."""
        kwargs.setdefault("tokenizer_factory",
                          getattr(w2v, "_tokenizer", None))
        return cls(w2v.vocab, np.asarray(w2v.syn0), **kwargs)

    # ---- host side: tokenize + pack ----
    def encode(self, texts: Union[str, Sequence[str]]) -> np.ndarray:
        """Pack texts into the (B, 2, L_pad) float32 wire tensor the
        jitted pool consumes — this is what a /v1/embed or text
        /v1/search request submits to the scheduler."""
        if isinstance(texts, str):
            texts = [texts]
        rows: List[List[int]] = []
        for text in texts:
            if not isinstance(text, str):
                raise ValueError(
                    "texts must be strings; got "
                    f"{type(text).__name__}")
            toks = self._tokenizer.create(text).get_tokens()
            ids = [self._index_of(t) for t in toks]
            ids = [i for i in ids if i >= 0][:self.max_tokens]
            rows.append(ids)
        width = max((len(r) for r in rows), default=0)
        l_pad = min(pow2_bucket(max(width, 1), lo=_MIN_TOKENS),
                    pow2_bucket(self.max_tokens))
        packed = np.zeros((len(rows), 2, l_pad), np.float32)
        for b, ids in enumerate(rows):
            n = min(len(ids), l_pad)
            packed[b, 0, :n] = ids[:n]
            packed[b, 1, :n] = 1.0
        return packed

    # ---- device side: the serving-model contract ----
    def output(self, packed) -> jnp.ndarray:
        """(B, 2, L) packed ids+mask -> (B, D) embeddings. This is
        the method BatchScheduler batches; the scheduler's pow2 row
        padding keeps B bucketed, encode() keeps L bucketed."""
        packed = jnp.asarray(packed, jnp.float32)
        if packed.ndim != 3 or packed.shape[1] != 2:
            raise ValueError(
                "embedder input must be (B, 2, L) packed ids+mask "
                f"from encode(); got {tuple(packed.shape)}")
        # clamp: padded/junk ids must stay inside the table (their
        # mask is 0 so the value never contributes)
        ids = jnp.clip(packed[:, 0, :], 0, self._table.shape[0] - 1)
        packed = jnp.stack([ids, packed[:, 1, :]], axis=1)
        return _mean_pool(self._table, packed,
                          normalize=self.normalize)

    def embed(self, texts: Union[str, Sequence[str]]) -> np.ndarray:
        """encode + pool in one host call (the non-serving path:
        tests, index build, oracle computation)."""
        return np.asarray(self.output(self.encode(texts)))

    # ---- introspection ----
    def __len__(self) -> int:
        return self._vocab_size

    def info(self) -> dict:
        return {"dim": self.dim, "vocab": self._vocab_size,
                "normalize": self.normalize,
                "max_tokens": self.max_tokens}
