"""deeplearning4j_tpu.retrieval — device-resident vector search.

The reference DL4J ships a nearest-neighbors REST server (VPTree) and
the Word2Vec family as first-class products; this package turns them
into a production retrieval subsystem: batched on-device embedding
(:mod:`~deeplearning4j_tpu.retrieval.embedder`) and top-k vector
search (:mod:`~deeplearning4j_tpu.retrieval.index` — a jitted
brute-force matmul index plus an IVF coarse quantizer), served through
the existing scheduler/router stack by
:mod:`deeplearning4j_tpu.serving.retrieval_backend`.
"""

from deeplearning4j_tpu.retrieval.index import (  # noqa: F401
    BruteForceIndex, IVFIndex, pow2_bucket,
)
from deeplearning4j_tpu.retrieval.embedder import (  # noqa: F401
    TextEmbedder,
)

__all__ = ["BruteForceIndex", "IVFIndex", "TextEmbedder",
           "pow2_bucket"]
