"""Device-resident vector indexes: brute-force matmul top-k + IVF.

The retrieval subsystem's data plane. Two index kinds behind one
contract:

- :class:`BruteForceIndex` — the exact baseline: one jitted matmul
  over the whole corpus plus ``lax.top_k``. Query batches are padded
  to power-of-two row counts and the corpus matrix to a power-of-two
  capacity, so XLA compiles O(log) executables as the index grows
  instead of one per size. ``add``/``remove`` are incremental:
  removes tombstone rows (masked out of the scores, reported in
  ``stats()``), and the store compacts when tombstones outnumber
  live rows or on capacity growth.
- :class:`IVFIndex` — the inverted-file coarse quantizer that scales
  past a single dense matmul's comfort zone: k-means (the jitted
  Lloyd step from ``clustering/kmeans.py``) partitions the corpus
  into ``nlist`` cells; a query scores only its ``nprobe`` nearest
  cells' members (gathered into one padded device call), trading
  recall for QPS. ``estimate_recall`` measures that trade against
  the exact answer on a sample of the corpus itself.

Scores are HIGHER-IS-BETTER for every metric: cosine similarity,
dot product, or negative squared euclidean distance. Missing results
(k larger than the live corpus, or an empty probe set) come back as
id ``-1`` with score ``-inf``.

Concurrency: mutations serialize on a writer lock and publish an
immutable snapshot (host + device arrays, generation-tagged);
searches read the current snapshot with one atomic attribute load and
never block writers — the single-writer / wait-free-reader discipline
the ``/v1/index`` admin verbs build on.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["BruteForceIndex", "IVFIndex", "pow2_bucket", "METRICS"]

METRICS = ("cosine", "dot", "euclidean")

# smallest corpus capacity: tiny indexes still get one stable compiled
# shape instead of a fresh executable per add
_MIN_CAPACITY = 64


def pow2_bucket(n: int, lo: int = 1) -> int:
    """The next power of two >= max(n, lo) — the shape-bucketing
    helper shared by query batches, top-k widths and capacities."""
    target = int(lo)
    n = int(n)
    while target < n:
        target *= 2
    return target


# ---------------------------------------------------------------------------
# jitted kernels (pure: inputs in, (scores, positions) out)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("k",))
def _dot_topk(q, mat, mask, k):
    """Top-k by dot product: q (B, D) @ mat (N, D).T with dead/pad
    rows masked to -inf. Cosine rides this kernel with both sides
    unit-normalized."""
    scores = q @ mat.T
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    return lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _l2_topk(q, mat, sq, mask, k):
    """Top-k by negative squared euclidean distance, expanded so the
    corpus norms ``sq`` are precomputed once per snapshot."""
    scores = (2.0 * (q @ mat.T) - sq[None, :]
              - jnp.sum(q * q, axis=1, keepdims=True))
    scores = jnp.where(mask[None, :], scores, -jnp.inf)
    return lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _gather_dot_topk(q, mat, idx, cmask, k):
    """IVF fine scoring: gather each query's candidate rows (idx
    (B, C) into mat) and top-k the per-query dot scores. Returns
    (scores, rows) with rows already mapped through idx."""
    cand = mat[idx]                              # (B, C, D)
    scores = jnp.einsum("bcd,bd->bc", cand, q)
    scores = jnp.where(cmask, scores, -jnp.inf)
    vals, pos = lax.top_k(scores, k)
    rows = jnp.take_along_axis(idx, pos, axis=1)
    return vals, rows


@functools.partial(jax.jit, static_argnames=("k",))
def _gather_l2_topk(q, mat, sq, idx, cmask, k):
    cand = mat[idx]
    scores = (2.0 * jnp.einsum("bcd,bd->bc", cand, q)
              - sq[idx] - jnp.sum(q * q, axis=1, keepdims=True))
    scores = jnp.where(cmask, scores, -jnp.inf)
    vals, pos = lax.top_k(scores, k)
    rows = jnp.take_along_axis(idx, pos, axis=1)
    return vals, rows


def _pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    if x.shape[0] == target:
        return x
    pad = np.zeros((target - x.shape[0],) + x.shape[1:], x.dtype)
    return np.concatenate([x, pad], axis=0)


class _Snapshot:
    """One immutable published view of the store. Searches hold a
    reference for their whole duration, so a concurrent compaction
    can never shift rows under a running device call."""

    __slots__ = ("mat", "sq", "mask", "mat_host", "row_ids",
                 "id_to_row", "live", "cap", "generation", "dead",
                 "lists", "centroids")

    def __init__(self, mat_host: np.ndarray, prepped: np.ndarray,
                 mask: np.ndarray, row_ids: np.ndarray,
                 id_to_row: Dict[int, int], live: int,
                 generation: int, dead: int = 0,
                 lists: Optional[List[np.ndarray]] = None,
                 centroids: Optional[np.ndarray] = None):
        self.mat_host = mat_host          # raw vectors (cap, D)
        self.mat = jnp.asarray(prepped)   # metric-prepped, on device
        self.sq = jnp.asarray(
            np.sum(prepped.astype(np.float64) ** 2,
                   axis=1).astype(np.float32))
        self.mask = jnp.asarray(mask)
        self.row_ids = row_ids            # external id per row, -1 dead
        self.id_to_row = id_to_row
        self.live = live
        self.cap = mat_host.shape[0]
        self.generation = generation
        self.dead = dead
        self.lists = lists                # IVF: row indices per cell
        self.centroids = centroids        # IVF: prepped (nlist, D)


class _FlatStore:
    """Capacity-managed flat vector store with tombstoned removes —
    the host half shared by both index kinds."""

    kind = "flat"

    def __init__(self, dim: int, metric: str = "cosine"):
        if metric not in METRICS:
            raise ValueError(f"unknown metric {metric!r}; known: "
                             f"{METRICS}")
        if int(dim) <= 0:
            raise ValueError("dim must be positive")
        self.dim = int(dim)
        self.metric = metric
        self._lock = threading.Lock()      # single writer at a time
        self._mat = np.zeros((0, self.dim), np.float32)
        self._row_ids = np.zeros(0, np.int64)
        self._mask = np.zeros(0, bool)
        self._id_to_row: Dict[int, int] = {}
        self._n = 0                        # append watermark
        self._dead = 0
        self._generation = 0
        self._snap: Optional[_Snapshot] = None
        with self._lock:
            self._publish()

    # ---- metric prep (host mirror of the kernels' expectations) ----
    def _prep(self, x: np.ndarray) -> np.ndarray:
        if self.metric == "cosine":
            n = np.linalg.norm(x, axis=1, keepdims=True)
            return (x / np.maximum(n, 1e-12)).astype(np.float32)
        return x.astype(np.float32)

    # ---- mutation (call with self._lock held) ----
    def _publish(self) -> None:
        self._generation += 1
        prepped = self._prep(self._mat) if self._mat.size \
            else self._mat
        self._snap = _Snapshot(
            self._mat, prepped, self._mask.copy(),
            self._row_ids.copy(), dict(self._id_to_row),
            live=len(self._id_to_row), generation=self._generation,
            dead=self._dead, **self._extra_snapshot())

    def _extra_snapshot(self) -> dict:
        return {}

    def _grow_to(self, need: int) -> None:
        """Compact + regrow the arrays to a pow2 capacity >= need
        (tombstones are dropped here — growth IS a compaction)."""
        live_rows = np.flatnonzero(self._mask)
        cap = pow2_bucket(need, lo=_MIN_CAPACITY)
        mat = np.zeros((cap, self.dim), np.float32)
        row_ids = np.full(cap, -1, np.int64)
        n = live_rows.size
        mat[:n] = self._mat[live_rows]
        row_ids[:n] = self._row_ids[live_rows]
        mask = np.zeros(cap, bool)
        mask[:n] = True
        self._mat, self._row_ids, self._mask = mat, row_ids, mask
        self._id_to_row = {int(i): r for r, i
                           in enumerate(row_ids[:n])}
        self._n, self._dead = n, 0
        self._on_rows_moved(live_rows)

    def _on_rows_moved(self, old_rows: np.ndarray) -> None:
        """Hook for subclasses carrying per-row sidecars (IVF cell
        assignments): ``old_rows[new_row]`` is the previous index of
        each surviving row."""

    def _compact_locked(self) -> None:
        self._grow_to(max(len(self._id_to_row), 1))

    def _tombstone(self, row: int) -> None:
        self._mask[row] = False
        ext = int(self._row_ids[row])
        self._row_ids[row] = -1
        self._id_to_row.pop(ext, None)
        self._dead += 1

    def _append_rows(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        """Upsert ``vecs`` under ``ids`` (existing ids are replaced).
        Caller holds the lock; caller publishes."""
        for ext in ids:
            row = self._id_to_row.get(int(ext))
            if row is not None:
                self._tombstone(row)
        if self._n + ids.size > self._mat.shape[0]:
            self._grow_to(len(self._id_to_row) + ids.size)
        start = self._n
        self._mat[start:start + ids.size] = vecs
        self._row_ids[start:start + ids.size] = ids
        self._mask[start:start + ids.size] = True
        for off, ext in enumerate(ids):
            self._id_to_row[int(ext)] = start + off
        self._n += ids.size
        if self._dead > max(len(self._id_to_row), 1):
            self._compact_locked()

    @staticmethod
    def _check_pair(ids, vectors, dim) -> Tuple[np.ndarray, np.ndarray]:
        ids = np.asarray(ids, np.int64).reshape(-1)
        vecs = np.asarray(vectors, np.float32)
        if vecs.ndim == 1:
            vecs = vecs[None, :]
        if vecs.ndim != 2 or vecs.shape[1] != dim:
            raise ValueError(
                f"vectors must be (n, {dim}); got {vecs.shape}")
        if ids.size != vecs.shape[0]:
            raise ValueError(
                f"{ids.size} ids for {vecs.shape[0]} vectors")
        if ids.size and np.any(ids < 0):
            raise ValueError("ids must be non-negative (id -1 is the "
                             "missing-result sentinel)")
        if np.unique(ids).size != ids.size:
            raise ValueError("duplicate ids within one add() call")
        return ids, vecs

    # ---- public mutation API ----
    def add(self, ids, vectors) -> int:
        """Upsert vectors under integer ids; returns the new
        generation."""
        ids, vecs = self._check_pair(ids, vectors, self.dim)
        with self._lock:
            if ids.size:
                self._append_rows(ids, vecs)
            self._publish()
            return self._generation

    def remove(self, ids) -> int:
        """Tombstone the given ids (unknown ids ignored); returns
        the number actually removed. Compacts once tombstones
        outnumber live rows."""
        removed = 0
        with self._lock:
            for ext in np.asarray(ids, np.int64).reshape(-1):
                row = self._id_to_row.get(int(ext))
                if row is not None:
                    self._tombstone(row)
                    removed += 1
            if removed:
                if self._dead > max(len(self._id_to_row), 1):
                    self._compact_locked()
                self._publish()
        return removed

    def compact(self) -> int:
        """Force tombstone compaction; returns the generation."""
        with self._lock:
            self._compact_locked()
            self._publish()
            return self._generation

    # ---- introspection ----
    @property
    def generation(self) -> int:
        snap = self._snap
        return snap.generation if snap is not None else 0

    def __len__(self) -> int:
        snap = self._snap
        return snap.live if snap is not None else 0

    def stats(self) -> dict:
        snap = self._snap
        return {"kind": self.kind, "metric": self.metric,
                "dim": self.dim, "vectors": snap.live,
                "tombstones": snap.dead, "capacity": snap.cap,
                "generation": snap.generation}

    def get(self, ext_id: int) -> Optional[np.ndarray]:
        snap = self._snap
        row = snap.id_to_row.get(int(ext_id))
        return None if row is None else snap.mat_host[row].copy()

    # ---- shared search plumbing ----
    @staticmethod
    def _empty_result(b: int, k: int) -> Tuple[np.ndarray, np.ndarray]:
        return (np.full((b, k), -1, np.int64),
                np.full((b, k), -np.inf, np.float32))

    def _check_queries(self, queries) -> np.ndarray:
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be (b, {self.dim}); got {q.shape}")
        return q

    @staticmethod
    def _finish(vals: np.ndarray, rows: np.ndarray,
                snap: _Snapshot, b: int, k: int
                ) -> Tuple[np.ndarray, np.ndarray]:
        """Trim padded device output to (b, k) and map internal rows
        to external ids (-inf scores become id -1)."""
        vals = np.asarray(vals)[:b, :k]
        rows = np.asarray(rows)[:b, :k]
        ids = snap.row_ids[rows]
        ids = np.where(np.isfinite(vals), ids, -1)
        if vals.shape[1] < k:            # corpus smaller than k
            pad = k - vals.shape[1]
            ids = np.concatenate(
                [ids, np.full((b, pad), -1, np.int64)], axis=1)
            vals = np.concatenate(
                [vals, np.full((b, pad), -np.inf, np.float32)],
                axis=1)
        return ids.astype(np.int64), vals.astype(np.float32)

    def _search_filtered(self, snap: _Snapshot, q: np.ndarray,
                         k: int, allow_ids) -> Tuple[np.ndarray,
                                                     np.ndarray]:
        """Restrict the search to an explicit id allow-list. Host
        numpy over the (small) allowed subset — filtered queries are
        per-request shaped and deliberately stay off the batched
        device path."""
        rows = [snap.id_to_row[int(i)] for i in allow_ids
                if int(i) in snap.id_to_row]
        b = q.shape[0]
        if not rows:
            return self._empty_result(b, k)
        rows = np.asarray(sorted(set(rows)), np.int64)
        sub = snap.mat_host[rows]
        qp = self._prep(q)
        subp = self._prep(sub)
        if self.metric == "euclidean":
            scores = (2.0 * (qp @ subp.T)
                      - np.sum(subp.astype(np.float64) ** 2, axis=1,
                               dtype=np.float64).astype(np.float32)
                      - np.sum(qp * qp, axis=1, keepdims=True))
        else:
            scores = qp @ subp.T
        kk = min(k, rows.size)
        order = np.argsort(-scores, axis=1)[:, :kk]
        vals = np.take_along_axis(scores, order, axis=1)
        ids = snap.row_ids[rows[order]]
        if kk < k:
            ids = np.concatenate(
                [ids, np.full((b, k - kk), -1, np.int64)], axis=1)
            vals = np.concatenate(
                [vals, np.full((b, k - kk), -np.inf, np.float32)],
                axis=1)
        return ids.astype(np.int64), vals.astype(np.float32)


class BruteForceIndex(_FlatStore):
    """Exact top-k by one jitted matmul over the whole corpus."""

    kind = "brute_force"

    def search(self, queries, k: int,
               nprobe: Optional[int] = None,
               allow_ids: Optional[Sequence[int]] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """(ids, scores), each (b, k). ``nprobe`` is accepted (and
        ignored) so both index kinds serve one call shape."""
        del nprobe
        q = self._check_queries(queries)
        if k <= 0:
            raise ValueError("k must be positive")
        snap = self._snap
        b = q.shape[0]
        if snap.live == 0:
            return self._empty_result(b, k)
        if allow_ids is not None:
            return self._search_filtered(snap, q, k, allow_ids)
        # pow2-bucketed shapes: query rows and top-k width each pad
        # up, so the compiled-executable count stays O(log^2), not
        # O(requests)
        k_dev = min(pow2_bucket(k), snap.cap)
        qp = _pad_rows(self._prep(q), pow2_bucket(b))
        if self.metric == "euclidean":
            vals, rows = _l2_topk(qp, snap.mat, snap.sq, snap.mask,
                                  k=k_dev)
        else:
            vals, rows = _dot_topk(qp, snap.mat, snap.mask, k=k_dev)
        return self._finish(vals, rows, snap, b, k)


class IVFIndex(_FlatStore):
    """Inverted-file index: k-means cells + nprobe-cell search."""

    kind = "ivf"

    def __init__(self, dim: int, nlist: int = 16,
                 metric: str = "cosine", seed: int = 0,
                 train_iters: int = 25):
        self.nlist = int(nlist)
        if self.nlist <= 0:
            raise ValueError("nlist must be positive")
        self.seed = int(seed)
        self.train_iters = int(train_iters)
        self._centroids: Optional[np.ndarray] = None  # prepped space
        self._assign = np.zeros(0, np.int32)
        super().__init__(dim, metric)

    # ---- training ----
    def train(self, vectors) -> "IVFIndex":
        """Fit the coarse quantizer on (a sample of) the corpus —
        the jitted Lloyd iteration from ``clustering/kmeans.py``
        runs the assignment/update steps on device. Must run before
        ``add``; re-training an index with resident vectors
        reassigns them."""
        from deeplearning4j_tpu.clustering.kmeans import (
            KMeansClustering)
        x = np.asarray(vectors, np.float32)
        if x.ndim != 2 or x.shape[1] != self.dim:
            raise ValueError(
                f"training vectors must be (n, {self.dim}); got "
                f"{x.shape}")
        if x.shape[0] < 1:
            raise ValueError("training needs at least one vector")
        k = min(self.nlist, x.shape[0])
        km = KMeansClustering(
            k, max_iterations=self.train_iters, seed=self.seed,
            distance="cosine" if self.metric == "cosine"
            else "euclidean")
        km.apply_to(x)
        # centroids live in the metric-prepped space (unit sphere for
        # cosine), matching what _prep does to queries and rows
        self._centroids = np.asarray(km.centroids, np.float32)
        with self._lock:
            if self._n:
                self._assign[:self._n] = self._assign_cells(
                    self._mat[:self._n])
            self._publish()
        return self

    def build(self, ids, vectors) -> "IVFIndex":
        """train + add in one call — the load-a-corpus path."""
        self.train(vectors)
        self.add(ids, vectors)
        return self

    @property
    def trained(self) -> bool:
        return self._centroids is not None

    def _assign_cells(self, vecs: np.ndarray) -> np.ndarray:
        """Nearest-centroid cell per row, in prepped space (squared
        euclidean there equals the metric's own ordering)."""
        v = self._prep(np.asarray(vecs, np.float32))
        c = self._centroids
        d2 = (np.sum(v ** 2, axis=1, keepdims=True)
              - 2.0 * (v @ c.T) + np.sum(c ** 2, axis=1)[None, :])
        return np.argmin(d2, axis=1).astype(np.int32)

    # ---- store hooks ----
    def add(self, ids, vectors) -> int:
        if self._centroids is None:
            raise ValueError(
                "IVF index is untrained — call train()/build() "
                "before add()")
        ids_arr, vecs = self._check_pair(ids, vectors, self.dim)
        with self._lock:
            if ids_arr.size:
                cells = self._assign_cells(vecs)
                self._pending_cells = cells
                try:
                    self._append_rows(ids_arr, vecs)
                finally:
                    del self._pending_cells
            self._publish()
            return self._generation

    def _append_rows(self, ids: np.ndarray, vecs: np.ndarray) -> None:
        if self._assign.shape[0] < self._mat.shape[0]:
            self._assign = np.resize(self._assign,
                                     self._mat.shape[0])
        start_before = self._n
        super()._append_rows(ids, vecs)
        if self._assign.shape[0] < self._mat.shape[0]:
            grown = np.full(self._mat.shape[0], -1, np.int32)
            grown[:self._assign.shape[0]] = self._assign
            self._assign = grown
        cells = getattr(self, "_pending_cells", None)
        if cells is not None:
            start = self._n - ids.size
            self._assign[start:start + ids.size] = cells
            del start_before

    def _on_rows_moved(self, old_rows: np.ndarray) -> None:
        if self._assign.size:
            moved = np.full(self._mat.shape[0], -1, np.int32)
            moved[:old_rows.size] = self._assign[old_rows]
            self._assign = moved
        else:
            self._assign = np.full(self._mat.shape[0], -1, np.int32)

    def _extra_snapshot(self) -> dict:
        if self._centroids is None:
            return {"lists": None, "centroids": None}
        lists: List[np.ndarray] = [
            np.zeros(0, np.int64)] * self._centroids.shape[0]
        if self._n:
            live = self._mask[:self._n]
            rows = np.flatnonzero(live)
            cells = self._assign[:self._n][live]
            order = np.argsort(cells, kind="stable")
            rows, cells = rows[order], cells[order]
            bounds = np.searchsorted(
                cells, np.arange(self._centroids.shape[0] + 1))
            lists = [rows[bounds[c]:bounds[c + 1]].astype(np.int64)
                     for c in range(self._centroids.shape[0])]
        return {"lists": lists, "centroids": self._centroids}

    def stats(self) -> dict:
        out = super().stats()
        out["nlist"] = self.nlist
        out["trained"] = self.trained
        snap = self._snap
        if snap is not None and snap.lists is not None:
            sizes = [int(r.size) for r in snap.lists]
            out["cells"] = {"count": len(sizes),
                            "max_size": max(sizes, default=0),
                            "empty": sum(1 for s in sizes if not s)}
        return out

    # ---- search ----
    def search(self, queries, k: int,
               nprobe: Optional[int] = None,
               allow_ids: Optional[Sequence[int]] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = self._check_queries(queries)
        if k <= 0:
            raise ValueError("k must be positive")
        snap = self._snap
        b = q.shape[0]
        if snap.live == 0 or snap.centroids is None:
            return self._empty_result(b, k)
        if allow_ids is not None:
            return self._search_filtered(snap, q, k, allow_ids)
        nlist = snap.centroids.shape[0]
        nprobe = nlist if nprobe is None \
            else max(1, min(int(nprobe), nlist))
        qp = self._prep(q)
        # coarse scoring on host: nlist is small (tens..hundreds), so
        # the (b, nlist) distance matrix is noise next to the fine
        # gather-matmul the device call does below
        c = snap.centroids
        d2 = (np.sum(qp ** 2, axis=1, keepdims=True)
              - 2.0 * (qp @ c.T) + np.sum(c ** 2, axis=1)[None, :])
        probes = np.argpartition(d2, nprobe - 1,
                                 axis=1)[:, :nprobe]
        cand = [np.concatenate([snap.lists[c] for c in row])
                for row in probes]
        width = max((r.size for r in cand), default=0)
        if width == 0:
            return self._empty_result(b, k)
        # candidate width pads to a SNAPSHOT-level constant (worst
        # case nprobe cells of the largest list), not the batch's own
        # max: per-batch widths vary with the query mix, and every
        # fresh pow2 width would be a steady-state XLA compile. This
        # way the gather shape is a function of (generation, nprobe,
        # k, batch bucket) only — static corpus, static shapes.
        max_list = max((r.size for r in snap.lists), default=0)
        c_pad = min(pow2_bucket(max(width, nprobe * max_list)),
                    pow2_bucket(snap.cap))
        idx = np.zeros((b, c_pad), np.int64)
        cmask = np.zeros((b, c_pad), bool)
        for i, r in enumerate(cand):
            idx[i, :r.size] = r
            cmask[i, :r.size] = True
        k_dev = min(pow2_bucket(k), c_pad)
        b_pad = pow2_bucket(b)
        qd = _pad_rows(qp, b_pad)
        idx = _pad_rows(idx, b_pad)
        cmask = _pad_rows(cmask, b_pad)
        if self.metric == "euclidean":
            vals, rows = _gather_l2_topk(qd, snap.mat, snap.sq,
                                         idx, cmask, k=k_dev)
        else:
            vals, rows = _gather_dot_topk(qd, snap.mat, idx, cmask,
                                          k=k_dev)
        return self._finish(vals, rows, snap, b, k)

    # ---- quality ----
    def estimate_recall(self, k: int = 10, sample: int = 16,
                        nprobe: Optional[int] = None,
                        seed: int = 0) -> Optional[float]:
        """recall@k of THIS index against the exact answer, probing
        with a seeded sample of its own resident vectors. None on an
        empty/untrained index. Exact reference is host numpy over
        the live rows — independent of the device kernels it
        grades."""
        snap = self._snap
        if snap is None or snap.live == 0 or snap.centroids is None:
            return None
        live_rows = np.flatnonzero(np.asarray(snap.mask))
        rng = np.random.default_rng(seed)
        take = min(int(sample), live_rows.size)
        qrows = rng.choice(live_rows, size=take, replace=False)
        queries = snap.mat_host[qrows]
        ids, _ = self.search(queries, k=k, nprobe=nprobe)
        qp = self._prep(queries)
        mp = self._prep(snap.mat_host[live_rows])
        if self.metric == "euclidean":
            scores = (2.0 * (qp @ mp.T)
                      - np.sum(mp * mp, axis=1)[None, :])
        else:
            scores = qp @ mp.T
        kk = min(k, live_rows.size)
        order = np.argsort(-scores, axis=1)[:, :kk]
        truth = snap.row_ids[live_rows[order]]
        hits = 0
        for got, want in zip(ids, truth):
            hits += len(set(int(g) for g in got if g >= 0)
                        & set(int(w) for w in want))
        return hits / max(truth.size, 1)
