"""K-Means clustering.

Mirrors nearestneighbor-core clustering/kmeans/KMeansClustering.java —
but the assignment/update steps are one jitted Lloyd iteration (full
(N,K) distance matrix on the MXU, segment-sum centroid update) instead
of per-point Java loops. k-means++ initialization included.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["KMeansClustering"]


@jax.jit
def _lloyd_step(points, centroids):
    # points (N,D), centroids (K,D)
    d2 = (jnp.sum(points ** 2, axis=1, keepdims=True)
          - 2 * points @ centroids.T
          + jnp.sum(centroids ** 2, axis=1)[None, :])
    assign = jnp.argmin(d2, axis=1)                       # (N,)
    onehot = jax.nn.one_hot(assign, centroids.shape[0],
                            dtype=points.dtype)           # (N,K)
    sums = onehot.T @ points                              # (K,D)
    counts = jnp.sum(onehot, axis=0)[:, None]
    new_centroids = jnp.where(counts > 0, sums / jnp.maximum(counts, 1),
                              centroids)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centroids, assign, inertia


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100,
                 tol: float = 1e-5, seed: int = 0,
                 init: str = "kmeans++", distance: str = "euclidean"):
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"Unsupported distance '{distance}'")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.init = init
        self.distance = distance
        self.centroids: Optional[np.ndarray] = None
        self.inertia: float = float("inf")

    @staticmethod
    def setup(k: int, max_iterations: int = 100,
              distance: str = "euclidean") -> "KMeansClustering":
        """Reference-style factory (KMeansClustering.setup)."""
        return KMeansClustering(k, max_iterations, distance=distance)

    def _prep(self, x: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            # spherical k-means: L2-normalize so squared-euclidean
            # ordering equals cosine ordering
            n = np.linalg.norm(x, axis=1, keepdims=True)
            return x / np.maximum(n, 1e-12)
        return x

    def _init_centroids(self, x: np.ndarray,
                        rng: np.random.Generator) -> np.ndarray:
        if self.init != "kmeans++":
            return x[rng.choice(x.shape[0], self.k, replace=False)]
        centroids = [x[rng.integers(0, x.shape[0])]]
        for _ in range(1, self.k):
            d2 = np.min(
                [np.sum((x - c) ** 2, axis=1) for c in centroids],
                axis=0)
            probs = d2 / max(d2.sum(), 1e-12)
            centroids.append(x[rng.choice(x.shape[0], p=probs)])
        return np.stack(centroids)

    def apply_to(self, points: np.ndarray) -> np.ndarray:
        """Fit; returns cluster assignments (reference applyTo returns a
        ClusterSet — assignments + centroids here)."""
        x = self._prep(np.asarray(points, np.float32))
        rng = np.random.default_rng(self.seed)
        c = jnp.asarray(self._init_centroids(x, rng))
        xj = jnp.asarray(x)
        prev = np.inf
        assign = None
        for it in range(self.max_iterations):
            c, assign, inertia = _lloyd_step(xj, c)
            inertia = float(inertia)
            if abs(prev - inertia) < self.tol * max(abs(prev), 1.0):
                break
            prev = inertia
        self.centroids = np.asarray(c)
        self.inertia = inertia
        return np.asarray(assign)

    fit_predict = apply_to

    def predict(self, points: np.ndarray) -> np.ndarray:
        x = jnp.asarray(self._prep(np.asarray(points, np.float32)))
        _, assign, _ = _lloyd_step(x, jnp.asarray(self.centroids))
        return np.asarray(assign)
