from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.quadtree import QuadTree, SpTree

__all__ = ["KMeansClustering", "VPTree", "KDTree", "QuadTree", "SpTree"]
