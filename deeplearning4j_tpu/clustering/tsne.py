"""Barnes-Hut t-SNE.

Mirrors deeplearning4j-core plot/BarnesHutTsne.java:65 (implements
Model; fit(X) learns a 2/3-d embedding): input-space affinities via
perplexity-calibrated Gaussian kernels on the k-NN graph (VPTree),
low-dim repulsion approximated with the SpTree (theta), gradient
descent with momentum + early exaggeration — the van der Maaten
Barnes-Hut algorithm the reference implements.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.quadtree import SpTree
from deeplearning4j_tpu.clustering.vptree import VPTree

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["BarnesHutTsne"]


class BarnesHutTsne:
    def __init__(self, *, n_components: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 n_iter: int = 500, early_exaggeration: float = 12.0,
                 exaggeration_iters: int = 100, momentum: float = 0.5,
                 final_momentum: float = 0.8, seed: int = 42):
        self.n_components = n_components
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.seed = seed
        self.embedding: Optional[np.ndarray] = None

    # -------------------------------------------------- affinities (P)
    def _binary_search_beta(self, dists: np.ndarray) -> np.ndarray:
        """Per-point precision for target perplexity (reference
        computeGaussianPerplexity)."""
        target = np.log(self.perplexity)
        beta = 1.0
        beta_min, beta_max = -np.inf, np.inf
        for _ in range(50):
            p = np.exp(-dists * beta)
            sum_p = max(p.sum(), 1e-12)
            h = np.log(sum_p) + beta * float((dists * p).sum()) / sum_p
            diff = h - target
            if abs(diff) < 1e-5:
                break
            if diff > 0:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else \
                    (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else \
                    (beta + beta_min) / 2
        p = np.exp(-dists * beta)
        return p / max(p.sum(), 1e-12)

    def _input_affinities(self, x: np.ndarray):
        n = x.shape[0]
        k = min(n - 1, int(3 * self.perplexity))
        tree = VPTree(x, seed=self.seed)
        rows, cols, vals = [], [], []
        for i in range(n):
            idx, dist = tree.search(x[i], k + 1)
            pairs = [(j, d) for j, d in zip(idx, dist) if j != i][:k]
            d2 = np.array([d * d for _, d in pairs])
            p = self._binary_search_beta(d2)
            for (j, _), pj in zip(pairs, p):
                rows.append(i)
                cols.append(j)
                vals.append(pj)
        P = {}
        for r, c, v in zip(rows, cols, vals):
            P[(r, c)] = P.get((r, c), 0.0) + v
            P[(c, r)] = P.get((c, r), 0.0) + v   # symmetrize
        total = sum(P.values())
        rows = np.array([k_[0] for k_ in P], np.int32)
        cols = np.array([k_[1] for k_ in P], np.int32)
        vals = np.array([v / total for v in P.values()], np.float64)
        return rows, cols, vals

    # ---------------------------------------------------------- fitting
    def fit(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        rows, cols, vals = self._input_affinities(x)
        rng = np.random.default_rng(self.seed)
        y = rng.normal(0, 1e-4, (n, self.n_components))
        gains = np.ones_like(y)
        velocity = np.zeros_like(y)

        for it in range(self.n_iter):
            exag = (self.early_exaggeration
                    if it < self.exaggeration_iters else 1.0)
            mom = (self.momentum if it < self.exaggeration_iters
                   else self.final_momentum)
            # attractive forces over the sparse P graph
            diff = y[rows] - y[cols]
            q = 1.0 / (1.0 + np.sum(diff ** 2, axis=1))
            coeff = (exag * vals * q)[:, None] * diff
            pos_f = np.zeros_like(y)
            np.add.at(pos_f, rows, coeff)
            # repulsive forces via Barnes-Hut tree
            tree = SpTree.build(y)
            neg_f = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                acc = np.zeros(self.n_components)
                z += tree.compute_non_edge_forces(y[i], self.theta, acc)
                neg_f[i] = acc
            z = max(z, 1e-12)
            grad = pos_f - neg_f / z
            # delta-bar-delta gains (reference update rule)
            gains = np.where(np.sign(grad) != np.sign(velocity),
                             gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            velocity = mom * velocity - self.learning_rate * gains * grad
            y = y + velocity
            y = y - y.mean(0)
        self.embedding = y
        return y

    fit_transform = fit
