"""VP-tree for exact nearest-neighbor search.

Mirrors nearestneighbor-core clustering/vptree/VPTree.java:48 (build)
and :471-508 (search): vantage-point partitioning by median distance,
branch-and-bound k-NN with a bounded priority queue. Distances:
euclidean / cosine (the reference's similarity functions).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["VPTree"]


class _Node:
    __slots__ = ("index", "threshold", "left", "right")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class VPTree:
    """NOTE on cosine: 1-cos violates the triangle inequality, which
    breaks VP-tree pruning. Internally cosine mode searches EUCLIDEAN
    distance on L2-normalized vectors (a true metric with identical
    ordering: ||a-b||² = 2(1-cos) on the unit sphere) and converts
    reported distances back to 1-cos."""

    def __init__(self, items: np.ndarray, distance: str = "euclidean",
                 seed: int = 0):
        self.items = np.asarray(items, np.float64)
        self.distance = distance
        if distance == "cosine":
            norms = np.linalg.norm(self.items, axis=1, keepdims=True)
            self._search_items = self.items / np.maximum(norms, 1e-12)
        else:
            self._search_items = self.items
        self._rng = np.random.default_rng(seed)
        idx = list(range(len(self.items)))
        self.root = self._build(idx)

    def _dist_many(self, i: int, others: np.ndarray) -> np.ndarray:
        diff = self._search_items[others] - self._search_items[i]
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _dist_point(self, q: np.ndarray, i: int) -> float:
        return float(np.linalg.norm(self._search_items[i] - q))

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        vp_pos = self._rng.integers(0, len(idx))
        vp = idx.pop(int(vp_pos))
        node = _Node(vp)
        if not idx:
            return node
        others = np.array(idx)
        dists = self._dist_many(vp, others)
        median = float(np.median(dists))
        node.threshold = median
        inner = [int(i) for i, d in zip(others, dists) if d < median]
        outer = [int(i) for i, d in zip(others, dists) if d >= median]
        node.left = self._build(inner)
        node.right = self._build(outer)
        return node

    def search(self, query: np.ndarray, k: int) -> Tuple[List[int],
                                                         List[float]]:
        """k nearest neighbors (reference search :471). Cosine mode
        returns 1-cos distances."""
        q = np.asarray(query, np.float64)
        if self.distance == "cosine":
            q = q / max(np.linalg.norm(q), 1e-12)
        heap: List[Tuple[float, int]] = []   # max-heap via negatives
        tau = [np.inf]

        def visit(node: Optional[_Node]):
            if node is None:
                return
            d = self._dist_point(q, node.index)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if node.left is None and node.right is None:
                return
            if d < node.threshold:
                visit(node.left)
                if d + tau[0] >= node.threshold:
                    visit(node.right)
            else:
                visit(node.right)
                if d - tau[0] <= node.threshold:
                    visit(node.left)

        visit(self.root)
        pairs = sorted((-nd, i) for nd, i in heap)
        dists = [d for d, _ in pairs]
        if self.distance == "cosine":
            dists = [d * d / 2.0 for d in dists]    # ||a-b||²/2 = 1-cos
        return [i for _, i in pairs], dists
