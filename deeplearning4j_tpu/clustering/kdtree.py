"""KD-tree (nearestneighbor-core clustering/kdtree/KDTree.java):
axis-cycling median splits, k-NN branch-and-bound search."""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["KDTree"]


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index: int, axis: int):
        self.index = index
        self.axis = axis
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        self.dims = self.points.shape[1]
        self.root = self._build(list(range(len(self.points))), 0)

    def insert(self, point) -> None:
        """Incremental insert (reference KDTree.insert)."""
        point = np.asarray(point, np.float64)[None, :]
        idx = len(self.points)
        self.points = np.concatenate([self.points, point])
        node = self.root
        axis = 0
        if node is None:
            self.root = _KDNode(idx, 0)
            return
        while True:
            if point[0, node.axis] < self.points[node.index, node.axis]:
                if node.left is None:
                    node.left = _KDNode(idx, (node.axis + 1) % self.dims)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _KDNode(idx, (node.axis + 1) % self.dims)
                    return
                node = node.right

    def _build(self, idx: List[int], depth: int) -> Optional[_KDNode]:
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.points[i, axis])
        mid = len(idx) // 2
        node = _KDNode(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def visit(node: Optional[_KDNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - q))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = q[node.axis] - self.points[node.index, node.axis]
            near, far = (node.left, node.right) if diff < 0 else \
                (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        pairs = sorted((-nd, i) for nd, i in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]

    def nearest(self, query) -> Tuple[int, float]:
        ids, ds = self.knn(query, 1)
        return ids[0], ds[0]
