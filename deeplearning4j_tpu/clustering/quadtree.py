"""QuadTree (2-d) and SpTree (n-d) for Barnes-Hut approximations.

Mirrors nearestneighbor-core clustering/quadtree/QuadTree.java and
clustering/sptree/SpTree.java: spatial subdivision with per-cell center
of mass, used by Barnes-Hut t-SNE to approximate repulsive forces in
O(N log N).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["QuadTree", "SpTree"]


class SpTree:
    """n-dimensional Barnes-Hut tree (SpTree.java). Cells split into
    2^d children; each keeps cumulative center of mass + count."""

    __slots__ = ("center", "width", "dim", "cum_center", "count",
                 "children", "point_index", "coords")

    def __init__(self, center: np.ndarray, width: np.ndarray,
                 coords: Optional[np.ndarray] = None):
        self.center = np.asarray(center, np.float64)
        self.width = np.asarray(width, np.float64)
        self.dim = len(self.center)
        self.cum_center = np.zeros(self.dim)
        self.count = 0
        self.children: Optional[List["SpTree"]] = None
        self.point_index: Optional[int] = None
        self.coords = coords          # full point array (shared refs)

    def _child_for(self, point: np.ndarray) -> int:
        idx = 0
        for d in range(self.dim):
            if point[d] > self.center[d]:
                idx |= (1 << d)
        return idx

    def _subdivide(self):
        self.children = []
        for ci in range(1 << self.dim):
            offs = np.array([(1 if (ci >> d) & 1 else -1)
                             for d in range(self.dim)], np.float64)
            self.children.append(
                SpTree(self.center + offs * self.width / 2,
                       self.width / 2, self.coords))

    def insert(self, point: np.ndarray, index: int):
        self.cum_center = (self.cum_center * self.count + point) / \
            (self.count + 1)
        self.count += 1
        if self.children is None:
            if self.point_index is None:
                self.point_index = index
                return
            old = self.point_index
            # duplicate points would subdivide forever; fold into mass
            if np.allclose(self.coords[old], point) or \
                    float(np.max(self.width)) < 1e-12:
                return
            # split and reinsert the resident point
            self.point_index = None
            self._subdivide()
            self.children[self._child_for(self.coords[old])].insert(
                self.coords[old], old)
        if self.children is not None:
            self.children[self._child_for(point)].insert(point, index)

    def compute_non_edge_forces(self, point: np.ndarray, theta: float,
                                neg_f: np.ndarray) -> float:
        """Barnes-Hut negative-force accumulation for t-SNE
        (SpTree.computeNonEdgeForces). Returns the partition-sum
        contribution."""
        if self.count == 0:
            return 0.0
        diff = point - self.cum_center
        d2 = float(diff @ diff)
        max_width = float(np.max(self.width) * 2)
        if self.children is None or \
                (d2 > 0 and max_width / np.sqrt(d2) < theta):
            if self.count == 1 and d2 == 0.0:
                return 0.0      # the point itself
            q = 1.0 / (1.0 + d2)
            mult = self.count * q
            neg_f += mult * q * diff
            return mult
        s = 0.0
        for ch in self.children:
            s += ch.compute_non_edge_forces(point, theta, neg_f)
        return s


def _build_sptree(points: np.ndarray) -> SpTree:
    points = np.asarray(points, np.float64)
    lo, hi = points.min(0), points.max(0)
    center = (lo + hi) / 2
    width = np.maximum((hi - lo) / 2 + 1e-9, 1e-9)
    tree = SpTree(center, width, coords=points)
    for i, p in enumerate(points):
        tree.insert(p, i)
    return tree


SpTree.build = staticmethod(_build_sptree)


class QuadTree(SpTree):
    """2-d specialization (QuadTree.java)."""

    @staticmethod
    def build(points: np.ndarray) -> "SpTree":
        assert np.asarray(points).shape[1] == 2, "QuadTree is 2-d"
        return _build_sptree(points)
