"""deeplearning4j_tpu.chaos — deterministic fault injection +
resilience primitives.

The robustness analogue of the observability subsystem: named
injection sites are threaded through checkpointing, the data path,
training, and serving; a seed-driven process-wide injector
(``chaos.install(plan, seed=...)``) fires declaratively-planned
faults at them, replayably; and the hardening the injections exercise
— the shared :mod:`~deeplearning4j_tpu.chaos.retry` policy, checkpoint
CRC verification/quarantine, the serving CircuitBreaker — lives next
door. See the README "Fault injection & resilience" section for the
plan schema and site table.

Stdlib-only on import (the data path imports this module at module
scope; counters and the flight recorder are reached lazily, only when
a fault actually fires).
"""

from deeplearning4j_tpu.chaos.injector import (  # noqa: F401
    ChaosError, ChaosIOError, ChaosOSError, Fault, FaultInjector,
    FaultPlan, FaultSpec, SITES, SimulatedCrashError, current,
    file_fault, hit, install, parse_plan, step_fault, uninstall,
)
from deeplearning4j_tpu.chaos.netproxy import (  # noqa: F401
    NET_KINDS, NET_SITES, NetChaosProxy, NetFault, NetSpec,
    NetworkPlan, parse_net_plan,
)
from deeplearning4j_tpu.chaos.retry import (  # noqa: F401
    DEFAULT_IO_RETRY, RetryPolicy, retrying_io,
)

__all__ = ["ChaosError", "ChaosIOError", "ChaosOSError", "Fault",
           "FaultInjector", "FaultPlan", "FaultSpec", "SITES",
           "SimulatedCrashError", "current", "file_fault", "hit",
           "install", "parse_plan", "step_fault", "uninstall",
           "NET_KINDS", "NET_SITES", "NetChaosProxy", "NetFault",
           "NetSpec", "NetworkPlan", "parse_net_plan",
           "DEFAULT_IO_RETRY", "RetryPolicy", "retrying_io"]
