"""Seeded, deterministic TCP fault proxy — network chaos as a plan.

Every chaos drill so far is in-process call-site injection
(`chaos/injector.py`): partitions, connection resets mid-body,
truncated responses, corrupted streams and half-open peers have never
actually crossed a socket. This module closes that gap: a
:class:`NetChaosProxy` fronts any TCP listener (a fleet replica's
HTTP port, the DPS1 parameter-server wire, the collector's scrape
path) and applies a declarative JSON **network plan** — same shape,
same determinism contract and same audit trail as the fault plans.

Topology::

    client ──TCP──> NetChaosProxy(listen_port) ──TCP──> upstream
                     │  per-connection fault evaluation (seeded)
                     │  net_chaos_faults_fired_total{site,kind}
                     └─ flight-recorder "net_chaos_fault" events

Proxy sites (where a proxy sits — one name per TCP hop, linted
against the README table by graftlint GL011):

==================== ====================================================
``net.replica``      the router↔replica HTTP hop: one proxy fronts
                     one replica listener (``serve-fleet
                     --net-chaos PLAN`` boots every subprocess
                     replica behind one)
``net.ps``           the DPS1 parameter-server wire (``train-ps
                     --net-chaos PLAN`` hands workers the proxy's
                     address instead of the server's)
``net.collector``    the collector→member scrape hop, proxied
                     INDEPENDENTLY of the router's path to the same
                     replica — asymmetric partitions
==================== ====================================================

Fault kinds (validated at plan-parse time; a typo'd kind fails
loudly instead of installing a plan that silently injects nothing):

``partition``  blackhole the hop for ``args.duration_s`` (default
               5.0) in ``args.direction`` ``both`` / ``inbound``
               (client→upstream) / ``outbound`` (upstream→client).
               In-flight connections stall while dark and are closed
               at heal (their bytes are gone — exactly what a real
               partition does to an open TCP stream); new
               connections hang unanswered until heal.
``reset``      a real RST (``SO_LINGER(1,0)`` close) after
               ``args.after_bytes`` bytes of the ``args.when``
               stream (``response`` default / ``request``).
``truncate``   clean FIN after ``args.after_bytes`` (default 64)
               response bytes — Content-Length now lies.
``corrupt``    seeded bit flips: ``args.n_flips`` (default 3) bit
               positions drawn from the per-connection rng over the
               first ``args.window`` (default 4096) bytes of the
               ``args.when`` stream. Offsets are ABSOLUTE stream
               offsets, so TCP chunking cannot perturb the flips.
``delay``      sleep ``args.delay_s`` (default 0.05) before
               forwarding each chunk of the ``args.when`` stream.
``throttle``   cap the ``args.when`` stream at ``args.bytes_per_s``
               (default 8192).
``half_open``  accept the connection, read and discard the request,
               never connect upstream, never answer — the classic
               wedged peer that only bounded read deadlines survive.

Determinism contract (mirrors the injector): each plan spec draws
from its OWN rng stream (``seed ^ crc32(site#spec_idx)``) exactly
once per connection whether or not an earlier spec fired, per-proxy
connection ordinals are assigned under a lock, and per-connection
byte mutations derive from ``seed ^ crc32(site#spec_idx#conn{n})``
— so the fired-fault log is a pure function of (plan, seed,
connection count) and replays from the recorded seed.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["NetFault", "NetSpec", "NetworkPlan", "NetChaosProxy",
           "NET_SITES", "NET_KINDS", "parse_net_plan"]


# the hop table docs cite; registered here so every name exists as a
# code literal in exactly one authoritative place (GL011 lints the
# README table against this dict)
NET_SITES: Dict[str, str] = {
    "net.replica": "the router↔replica HTTP hop (one proxy per "
                   "replica listener)",
    "net.ps": "the DPS1 parameter-server wire (workers dial the "
              "proxy instead of the server)",
    "net.collector": "the collector→member scrape hop, proxied "
                     "independently of the router's path "
                     "(asymmetric partitions)",
}

# every kind any NetChaosProxy interprets — validated at plan-parse
# time and linted three-way by GL011 (this dict vs the `.kind`
# comparisons in the proxy vs the README kind table)
NET_KINDS: Dict[str, str] = {
    "partition": "blackhole the hop for duration_s (direction: "
                 "both/inbound/outbound); heal dooms in-flight "
                 "connections",
    "reset": "RST after after_bytes bytes of the when-stream",
    "truncate": "clean FIN after after_bytes response bytes",
    "corrupt": "seeded bit flips at absolute stream offsets",
    "delay": "sleep delay_s before forwarding each chunk",
    "throttle": "cap the stream at bytes_per_s",
    "half_open": "accept, swallow the request, never answer",
}

_DIRECTIONS = frozenset({"both", "inbound", "outbound"})
_WHEN = frozenset({"request", "response"})


class _CloseConn(Exception):
    """Internal: a shaper decided this connection dies now, after
    ``flush`` (the allowed prefix of the current chunk) is sent."""

    def __init__(self, rst: bool, flush: bytes = b""):
        self.rst = rst
        self.flush = flush


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

class NetSpec:
    """One declarative rule: WHERE (``site`` — which hop's proxies
    apply it, optionally narrowed to one proxy ``instance`` by
    name), WHAT (``kind``), WHEN (``p`` per-connection probability
    or ``at`` — explicit 1-based connection ordinals), bounded by
    ``max_fires``; ``args`` parameterizes the kind."""

    __slots__ = ("site", "kind", "p", "at", "max_fires", "args",
                 "instance")

    def __init__(self, site: str, kind: str, p: float = 0.0,
                 at: Optional[List[int]] = None,
                 max_fires: Optional[int] = None,
                 args: Optional[dict] = None,
                 instance: Optional[str] = None):
        if site not in NET_SITES:
            raise ValueError(
                f"unknown network-chaos site {site!r}; known sites: "
                f"{sorted(NET_SITES)}")
        if kind not in NET_KINDS:
            raise ValueError(
                f"unknown network-fault kind {kind!r}; known kinds: "
                f"{sorted(NET_KINDS)}")
        if not (at or p > 0.0):
            raise ValueError(
                f"network-fault spec for {site!r}/{kind!r} can never "
                "fire: give it p > 0 or an 'at' schedule")
        args = dict(args or {})
        d = args.get("direction", "both")
        if d not in _DIRECTIONS:
            raise ValueError(
                f"bad direction {d!r}; one of {sorted(_DIRECTIONS)}")
        w = args.get("when", "response")
        if w not in _WHEN:
            raise ValueError(
                f"bad when {w!r}; one of {sorted(_WHEN)}")
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.at = None if at is None else {int(n) for n in at}
        self.max_fires = max_fires
        self.args = args
        self.instance = instance

    @classmethod
    def from_dict(cls, d: dict) -> "NetSpec":
        known = {"site", "kind", "p", "at", "max_fires", "args",
                 "instance"}
        extra = set(d) - known
        if extra:
            raise ValueError(
                f"unknown network-fault spec key(s) {sorted(extra)}; "
                f"known: {sorted(known)}")
        return cls(d["site"], d["kind"], p=d.get("p", 0.0),
                   at=d.get("at"), max_fires=d.get("max_fires"),
                   args=d.get("args"), instance=d.get("instance"))

    def to_dict(self) -> dict:
        out = {"site": self.site, "kind": self.kind}
        if self.p:
            out["p"] = self.p
        if self.at is not None:
            out["at"] = sorted(self.at)
        if self.max_fires is not None:
            out["max_fires"] = self.max_fires
        if self.args:
            out["args"] = dict(self.args)
        if self.instance is not None:
            out["instance"] = self.instance
        return out


class NetworkPlan:
    def __init__(self, faults: List[NetSpec],
                 seed: Optional[int] = None):
        self.faults = list(faults)
        self.seed = seed

    def to_dict(self) -> dict:
        out = {"faults": [f.to_dict() for f in self.faults]}
        if self.seed is not None:
            out["seed"] = self.seed
        return out


def parse_net_plan(plan) -> NetworkPlan:
    """Accepts a :class:`NetworkPlan`, a list of spec dicts, a dict
    ``{"seed": ..., "faults": [...]}``, a JSON string of either, or
    a path to a JSON file — the same input forms as the injector's
    ``parse_plan``."""
    if isinstance(plan, NetworkPlan):
        return plan
    if isinstance(plan, str):
        text = plan.strip()
        if not text.startswith(("{", "[")):
            with open(plan) as f:
                text = f.read()
        plan = json.loads(text)
    if isinstance(plan, list):
        plan = {"faults": plan}
    if not isinstance(plan, dict):
        raise TypeError(f"cannot parse a network plan from "
                        f"{type(plan).__name__}")
    faults = [s if isinstance(s, NetSpec) else NetSpec.from_dict(s)
              for s in plan.get("faults", [])]
    seed = plan.get("seed")
    return NetworkPlan(faults, None if seed is None else int(seed))


# ---------------------------------------------------------------------------
# the proxy
# ---------------------------------------------------------------------------

class NetFault:
    """One fired network fault, shaping one connection (or, for
    ``partition``, the whole proxy)."""

    __slots__ = ("site", "kind", "args", "ordinal", "spec_idx")

    def __init__(self, site: str, kind: str, args: dict,
                 ordinal: int, spec_idx: int):
        self.site = site
        self.kind = kind
        self.args = args
        self.ordinal = ordinal
        self.spec_idx = spec_idx

    def __repr__(self):
        return (f"NetFault(site={self.site!r}, kind={self.kind!r}, "
                f"conn#{self.ordinal})")


class _Shaper:
    """Per-connection stream mutator for one fired fault. Tracks
    absolute stream offsets per direction so TCP chunk boundaries
    cannot perturb where a reset/truncate/corrupt lands."""

    def __init__(self, fault: NetFault, rng: random.Random):
        self.fault = fault
        self.when = fault.args.get("when", "response")
        self.after = int(fault.args.get("after_bytes",
                                        64 if fault.kind == "truncate"
                                        else 0))
        self.delay_s = float(fault.args.get("delay_s", 0.05))
        self.bps = float(fault.args.get("bytes_per_s", 8192.0))
        self._sent = {"request": 0, "response": 0}
        self._flips: Dict[int, int] = {}
        if fault.kind == "corrupt":
            window = int(fault.args.get("window", 4096))
            n_flips = int(fault.args.get("n_flips", 3))
            for _ in range(n_flips):
                off = rng.randrange(max(1, window))
                self._flips[off] = rng.randrange(8)

    def shape(self, stream: str, data: bytes) -> bytes:
        """Mutate (or gate) one chunk of ``stream`` ("request" |
        "response"); raises :class:`_CloseConn` when the fault says
        the connection dies here."""
        f = self.fault
        start = self._sent[stream]
        self._sent[stream] = start + len(data)
        if stream != self.when:
            return data
        if f.kind == "delay":
            time.sleep(self.delay_s)
        elif f.kind == "throttle":
            time.sleep(len(data) / max(1.0, self.bps))
        elif f.kind == "corrupt":
            buf = bytearray(data)
            for off, bit in self._flips.items():
                if start <= off < start + len(buf):
                    buf[off - start] ^= (1 << bit)
            data = bytes(buf)
        elif f.kind == "truncate":
            if start + len(data) > self.after:
                keep = max(0, self.after - start)
                raise _CloseConn(rst=False, flush=data[:keep])
        elif f.kind == "reset":
            if start + len(data) >= self.after:
                keep = max(0, self.after - start)
                raise _CloseConn(rst=True, flush=data[:keep])
        return data


class NetChaosProxy:
    """A TCP proxy fronting ``upstream`` that applies a
    :class:`NetworkPlan` deterministically, one evaluation per
    accepted connection.

    Mirrors :class:`chaos.injector.FaultInjector`'s contract:
    per-spec rng streams, per-proxy connection counter, first
    matching spec wins, every matching p-spec draws exactly once per
    connection, ``max_fires`` budgets live on the proxy. Fired
    faults count as ``net_chaos_faults_fired_total{site,kind}``,
    land in the flight recorder, and append to :attr:`fault_log` —
    two runs with the same (plan, seed, connection count) produce
    identical logs.
    """

    def __init__(self, upstream: Tuple[str, int], plan=None,
                 seed: Optional[int] = None, site: str = "net.replica",
                 name: Optional[str] = None,
                 listen_host: str = "127.0.0.1",
                 listen_port: int = 0):
        if site not in NET_SITES:
            raise ValueError(
                f"unknown network-chaos site {site!r}; known sites: "
                f"{sorted(NET_SITES)}")
        self.upstream = (upstream[0], int(upstream[1]))
        self.plan = parse_net_plan(plan if plan is not None else [])
        if seed is None:
            seed = self.plan.seed
        if seed is None:
            import os
            seed = int.from_bytes(os.urandom(4), "big")
        self.seed = int(seed)
        self.site = site
        # the name keys the rng streams: the fleet names proxies
        # "replica-<id>" so each replica's fire pattern is distinct
        # AND replayable (an ephemeral upstream port would be neither)
        self.name = name or site
        self.listen_host = listen_host
        self._listen_port = int(listen_port)
        self._lock = threading.Lock()
        self._rngs: Dict[int, random.Random] = {}
        self._spec_fired: List[int] = [0] * len(self.plan.faults)
        self.hits = 0
        self.fired_total = 0
        self.fault_log: List[dict] = []
        self._partition_until = 0.0
        self._partition_dir = "both"
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        if self._listener is None:
            raise RuntimeError("proxy not started")
        return self._listener.getsockname()[1]

    def start(self) -> "NetChaosProxy":
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self.listen_host, self._listen_port))
        ls.listen(128)
        ls.settimeout(0.2)
        # a FRESH stop event per generation, handed to every thread
        # this generation spawns: a restart can never revive a
        # stopping predecessor's pumps
        stop = threading.Event()
        with self._lock:
            self._listener = ls
            self._stop = stop
            t = threading.Thread(
                target=self._accept_loop, args=(ls, stop),
                name=f"netchaos-{self.name}", daemon=True)
            self._accept_thread = t
        t.start()
        logger.warning(
            "net-chaos: proxy %s up on %s:%d -> %s:%d (%d spec(s), "
            "seed=%d — replay with this seed)", self.name,
            self.listen_host, self.port, self.upstream[0],
            self.upstream[1], len(self.plan.faults), self.seed)
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._accept_thread = self._accept_thread, None
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            ls, self._listener = self._listener, None
            conns = list(self._conns)
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        for s in conns:
            try:
                s.close()
            except OSError:
                pass

    # -- manual triggers (tests drive partitions on a wall clock, not
    # -- a connection ordinal) ---------------------------------------------

    def partition(self, duration_s: float,
                  direction: str = "both") -> None:
        """Blackhole the hop for ``duration_s`` starting NOW, as if a
        ``partition`` spec had fired on this connection ordinal."""
        if direction not in _DIRECTIONS:
            raise ValueError(
                f"bad direction {direction!r}; one of "
                f"{sorted(_DIRECTIONS)}")
        with self._lock:
            n = self.hits
        f = NetFault(self.site, "partition",
                     {"duration_s": float(duration_s),
                      "direction": direction}, n, -1)
        self._apply_partition(f)
        self._account(f)

    def heal(self) -> None:
        """End an active partition early."""
        with self._lock:
            self._partition_until = 0.0

    def partitioned(self) -> bool:
        with self._lock:
            return time.monotonic() < self._partition_until

    # -- plan evaluation ---------------------------------------------------

    def _rng(self, spec_idx: int) -> random.Random:
        rng = self._rngs.get(spec_idx)
        if rng is None:
            rng = random.Random(self.seed ^ zlib.crc32(
                f"{self.name}#{spec_idx}".encode()))
            self._rngs[spec_idx] = rng
        return rng

    def _conn_rng(self, spec_idx: int, ordinal: int) -> random.Random:
        return random.Random(self.seed ^ zlib.crc32(
            f"{self.name}#{spec_idx}#conn{ordinal}".encode()))

    def _hit(self) -> Tuple[int, Optional[NetFault]]:
        """One accepted connection: first matching spec wins; every
        matching p-spec draws exactly once so each spec's stream is a
        pure function of the connection count."""
        with self._lock:
            self.hits += 1
            n = self.hits
            fired: Optional[NetFault] = None
            for i, spec in enumerate(self.plan.faults):
                if spec.site != self.site:
                    continue
                if spec.instance is not None \
                        and spec.instance != self.name:
                    continue
                if spec.at is not None:
                    want = n in spec.at
                else:
                    want = self._rng(i).random() < spec.p
                if not want:
                    continue
                if (spec.max_fires is not None
                        and self._spec_fired[i] >= spec.max_fires):
                    continue
                if fired is None:
                    self._spec_fired[i] += 1
                    fired = NetFault(self.site, spec.kind, spec.args,
                                     n, i)
            if fired is not None:
                self.fired_total += 1
        if fired is not None:
            self._account(fired)
        return n, fired

    def _account(self, fault: NetFault) -> None:
        with self._lock:
            self.fault_log.append({"conn": fault.ordinal,
                                   "kind": fault.kind,
                                   "spec": fault.spec_idx})
        logger.warning(
            "net-chaos: %s fault fired on %s (conn #%d)",
            fault.kind, self.name, fault.ordinal)
        try:
            from deeplearning4j_tpu.observability.registry import (
                safe_inc)
            safe_inc("net_chaos_faults_fired_total",
                     help="network faults fired by the chaos proxy",
                     labels={"site": fault.site, "kind": fault.kind})
        except Exception:
            pass
        try:
            from deeplearning4j_tpu.observability import flight_recorder
            rec = flight_recorder.get_recorder()
            if rec is not None:
                rec.record("net_chaos_fault", site=fault.site,
                           kind=fault.kind, ordinal=fault.ordinal,
                           proxy=self.name)
        except Exception:
            pass

    # -- data path ---------------------------------------------------------

    def _apply_partition(self, fault: NetFault) -> None:
        dur = float(fault.args.get("duration_s", 5.0))
        with self._lock:
            self._partition_until = time.monotonic() + dur
            self._partition_dir = fault.args.get("direction", "both")

    def _blocked(self, stream: str) -> bool:
        with self._lock:
            if time.monotonic() >= self._partition_until:
                return False
            d = self._partition_dir
        if d == "both":
            return True
        return (d == "inbound") if stream == "request" \
            else (d == "outbound")

    def _accept_loop(self, ls: socket.socket,
                     stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                conn, _addr = ls.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            n, fault = self._hit()
            if fault is not None and fault.kind == "partition":
                self._apply_partition(fault)
            threading.Thread(
                target=self._handle, args=(conn, n, fault, stop),
                name=f"netchaos-conn-{self.name}-{n}",
                daemon=True).start()

    def _track(self, sock: socket.socket, add: bool) -> None:
        with self._lock:
            if add:
                self._conns.add(sock)
            else:
                self._conns.discard(sock)

    def _handle(self, client: socket.socket, ordinal: int,
                fault: Optional[NetFault],
                stop: threading.Event) -> None:
        self._track(client, True)
        upstream: Optional[socket.socket] = None
        try:
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                              1)
            if fault is not None and fault.kind == "half_open":
                # the wedged peer: swallow the request, never answer
                self._drain_until_stop(client, stop)
                return
            # a partition (this connection's own fault, or one already
            # active) blackholes the dial when the request direction
            # is dark: hang, then die at heal — the client's bounded
            # deadline is what saves it. An outbound-only partition
            # still lets the request REACH upstream; the response
            # pump stalls instead.
            if self._blocked("request"):
                self._stall_through_partition(stop)
                return
            upstream = socket.create_connection(self.upstream,
                                                timeout=5.0)
            upstream.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            self._track(upstream, True)
            shaper = None
            if fault is not None and fault.kind not in ("partition",
                                                        "half_open"):
                shaper = _Shaper(fault, self._conn_rng(
                    fault.spec_idx, ordinal))
            done = threading.Event()
            rst = [False]
            t = threading.Thread(
                target=self._pump,
                args=(client, upstream, "request", shaper, done, rst,
                      stop),
                daemon=True)
            t.start()
            self._pump(upstream, client, "response", shaper, done,
                       rst, stop)
            done.set()
            t.join(timeout=5.0)
            if rst[0]:
                # a real RST, not a FIN: discard the send buffer
                try:
                    client.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
                except OSError:
                    pass
        except OSError:
            pass
        finally:
            for s in (upstream, client):
                if s is None:
                    continue
                self._track(s, False)
                try:
                    s.close()
                except OSError:
                    pass

    def _drain_until_stop(self, sock: socket.socket,
                          stop: threading.Event) -> None:
        sock.settimeout(0.2)
        while not stop.is_set():
            try:
                if not sock.recv(65536):
                    return
            except socket.timeout:
                continue
            except OSError:
                return

    def _stall_through_partition(self,
                                 stop: threading.Event) -> None:
        while not stop.is_set() and self._blocked("request"):
            time.sleep(0.05)
        # heal dooms the connection: fall through to close

    def _pump(self, src: socket.socket, dst: socket.socket,
              stream: str, shaper: Optional[_Shaper],
              done: threading.Event, rst: List[bool],
              stop: threading.Event) -> None:
        src.settimeout(0.2)
        while not stop.is_set() and not done.is_set():
            if self._blocked(stream):
                # stall while dark; the connection is doomed at heal
                while not stop.is_set() and self._blocked(stream):
                    time.sleep(0.05)
                break
            try:
                data = src.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            if shaper is not None:
                try:
                    data = shaper.shape(stream, data)
                except _CloseConn as c:
                    if c.flush:
                        try:
                            dst.sendall(c.flush)
                        except OSError:
                            pass
                    if c.rst:
                        rst[0] = True
                    break
            try:
                dst.sendall(data)
            except OSError:
                break
        done.set()
        # half-close toward the destination so well-behaved peers see
        # EOF promptly even if the other pump is still mid-stream
        try:
            dst.shutdown(socket.SHUT_WR)
        except OSError:
            pass
