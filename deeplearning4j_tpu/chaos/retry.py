"""Shared retry policy: bounded attempts, exponential backoff with
full jitter, deadline-aware budget.

One policy object, used everywhere transient I/O is retried (data
iterators, dataset fetchers) — retry behaviour is a resilience
POLICY, and a fix to it must not silently miss a call site. The
backoff follows the standard full-jitter scheme: attempt ``k`` sleeps
``uniform(0, min(max_delay, base_delay * multiplier**k))``, which
de-correlates a thundering herd of retriers while keeping the
expected wait half the deterministic schedule.

Deadline awareness: ``call(..., deadline=t)`` never sleeps past a
``time.monotonic()`` deadline — when the next backoff would overrun
the budget, the last failure is raised immediately instead of burning
the caller's remaining time asleep.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["RetryPolicy", "DEFAULT_IO_RETRY", "retrying_io"]


class RetryPolicy:
    """Immutable-ish retry policy; ``call`` runs a function under it.

    ``retry_on`` is the default tuple of exception types considered
    transient; anything else propagates on the first failure.
    """

    def __init__(self, max_attempts: int = 6,
                 base_delay: float = 0.02, max_delay: float = 1.0,
                 multiplier: float = 2.0, jitter: bool = True,
                 retry_on: Tuple[Type[BaseException], ...] = (OSError,),
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None,
                 name: str = "io"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.retry_on = retry_on
        self.name = name
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._lock = threading.Lock()

    def delay(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        cap = min(self.max_delay,
                  self.base_delay * (self.multiplier ** attempt))
        if not self.jitter:
            return cap
        with self._lock:               # Random() is not thread-safe
            return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable, *args,
             retry_on: Optional[Tuple[Type[BaseException], ...]] = None,
             deadline: Optional[float] = None, **kwargs):
        """Run ``fn(*args, **kwargs)``; retry transient failures with
        backoff. ``deadline`` is an absolute ``time.monotonic()``
        budget: the policy never sleeps past it."""
        retry_on = self.retry_on if retry_on is None else retry_on
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                attempt += 1
                if attempt >= self.max_attempts:
                    raise
                d = self.delay(attempt - 1)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or d > remaining:
                        # sleeping would overrun the budget: fail now
                        # with the real error, not a timeout later
                        raise
                self._count_retry()
                logger.debug("retry %d/%d after %r (backoff %.3fs)",
                             attempt, self.max_attempts - 1, e, d)
                self._sleep(d)

    def _count_retry(self) -> None:
        try:
            from deeplearning4j_tpu.observability.registry import (
                safe_inc)
            safe_inc("retry_attempts_total",
                     help="transient failures retried with backoff",
                     labels={"policy": self.name})
        except Exception:
            pass


# The shared data-path policy (iterators + fetchers). Six attempts
# with 20ms..1s full-jitter backoff rides out injected fault bursts
# (p=0.2 per hit -> ~6e-5 residual failure per batch) and real NFS
# blips without turning a dead disk into a hang.
DEFAULT_IO_RETRY = RetryPolicy(max_attempts=6, base_delay=0.02,
                               max_delay=1.0, name="io")


def retrying_io(site: str, fn: Callable):
    """THE data-path guard: hit chaos ``site``, run ``fn``, retry
    transient (injected or real) I/O failures under
    :data:`DEFAULT_IO_RETRY`. One shared implementation for every
    batch/file producer, so a fix to the pattern cannot miss a call
    site."""
    from deeplearning4j_tpu.chaos.injector import step_fault

    def attempt():
        step_fault(site)
        return fn()

    return DEFAULT_IO_RETRY.call(attempt)
