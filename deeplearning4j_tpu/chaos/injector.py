"""Deterministic, seed-driven fault injection.

The repo's fault-tolerance claims (ElasticTrainer restart ==
uninterrupted, serving admission control, worker-crash sweeps) were
until now proven only against hand-rolled test doubles. This module
makes failure a first-class, *replayable* input: a declarative
**fault plan** names injection sites threaded through the stack and
what should go wrong there, a process-wide :class:`FaultInjector`
(``chaos.install(plan, seed=...)``) decides — deterministically —
when each fault fires, and every fired fault is counted on the
unified MetricsRegistry (``chaos_faults_fired_total``) and recorded
by the flight recorder, so a chaotic run leaves the same audit trail
a real incident would.

Determinism contract: each site draws from its OWN rng stream
(derived from ``seed`` + the site name), and each site keeps its own
hit counter, so the fire pattern at one site is a pure function of
(plan, seed, number of hits at that site) — thread interleaving
ACROSS sites cannot perturb it. Replaying a recorded seed replays
the faults.

Injection sites (each name is a string literal at its call site —
the docs lint checks the README table against these):

==================== ====================================================
``checkpoint.write`` ``util/model_serializer.write_model`` — after the
                     zip is written (kinds: ``truncate``, ``corrupt``,
                     ``enospc``, ``error``)
``checkpoint.read``  ``util/model_serializer.restore_model`` — before
                     the zip is opened (``truncate``/``corrupt`` rot the
                     file at rest; ``error`` raises a transient IOError)
``data.fetch``       batch production in ``data/iterators.py``
                     (``error``, ``slow``) — retried by the shared
                     retry policy
``data.load``        real-file reads in ``data/fetchers.py``
                     (``error``, ``slow``)
``train.step``       ``train/fault_tolerance.ElasticTrainer`` right
                     before the train step (``crash``, ``hang``,
                     ``nan`` — the nan_injection fixture's poison, as a
                     plan-driven site — and ``sigterm``, which delivers
                     a REAL ``SIGTERM`` to the process at the seeded
                     ordinal: preemption as a replayable plan entry)
``serving.worker.step`` the serving backends' device step in
                     ``serving/scheduler.py`` / ``serving/continuous.py``
                     (``crash``, ``hang``, ``poison``)
``serving.replica``  one request routed by ``serving/router.py`` —
                     the WHOLE-replica fault site (``kill``: hard-stop
                     the replica at ``args.replica`` mid-load, the
                     seed-replayable SIGKILL; ``hang``/``slow``: stall
                     every handler on it by ``args.delay_s``, auto-
                     recovering after ``args.for_s`` when given)
``serving.replica.boot`` one replica BOOT in ``serving/fleet.py``
                     (``boot_fail``: the boot raises before the
                     listener opens — ``fleet.grow()`` retries with
                     bounded exponential backoff so the autoscaler's
                     control loop never wedges; ``boot_slow``: the
                     boot stalls ``args.delay_s`` first)
``parallel.device``  ``parallel/wrapper.ParallelWrapper`` right before
                     each data-parallel mesh step (``crash``, and
                     ``loss`` — simulate losing one mesh device; the
                     wrapper shrinks the mesh and continues)
``ps.push.drop``     one compressed-delta push received by the
                     parameter server (``drop``: swallow it unacked —
                     the worker retries the same sequence number and
                     the dedupe table keeps the retry idempotent)
``ps.pull.timeout``  one parameter pull served by the PS
                     (``timeout``: swallow the reply — the worker's
                     deadline expires and it re-pulls)
``ps.server.restart`` one PS push applied (``restart``:
                     crash-restart the server from its newest
                     durable checkpoint; workers reconnect)
``serving.rollout``  one RolloutController deployment step in
                     ``serving/rollout.py`` — canary boot and each
                     expansion replace (``bad_version``: the candidate
                     serves NaN-poisoned outputs, the gate must catch
                     it; ``slow_version``: the candidate's predict path
                     stalls ``args.delay_s`` per call, the latency gate
                     must catch it; ``stall``: the expansion step hangs
                     ``args.delay_s`` — operator ``abort`` still works)
==================== ====================================================

Generic kinds every site understands via :func:`step_fault`:
``crash`` (raise :class:`SimulatedCrashError`), ``hang`` / ``slow``
(sleep ``args.delay_s``), ``error`` (raise :class:`ChaosIOError` — an
``IOError``, so retry policies treat it as transient), ``enospc``
(raise :class:`ChaosOSError` with ``errno.ENOSPC``). File kinds
handled by :func:`file_fault`: ``truncate`` (cut the file to
``args.keep_frac``, default 0.5) and ``corrupt`` (overwrite a window
of bytes mid-file). Site-specific kinds (``nan``, ``poison``) are
returned to the call site to interpret.
"""

from __future__ import annotations

import errno
import json
import logging
import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["Fault", "FaultSpec", "FaultPlan", "FaultInjector",
           "ChaosError", "SimulatedCrashError", "ChaosIOError",
           "ChaosOSError", "SITES", "parse_plan", "install",
           "uninstall", "current", "hit", "step_fault", "file_fault"]


# ---------------------------------------------------------------------------
# typed injected failures
# ---------------------------------------------------------------------------

class ChaosError(RuntimeError):
    """Marker base for every injected failure: handlers can always
    tell a drill from a real incident."""


class SimulatedCrashError(ChaosError):
    """An injected process/worker crash (kind ``crash``)."""


class ChaosIOError(ChaosError, IOError):
    """An injected transient I/O failure (kind ``error``). Subclasses
    ``IOError`` so retry policies that retry ``OSError`` treat it
    exactly like the real thing."""


class ChaosOSError(ChaosError, OSError):
    """An injected OS-level failure with a real errno (kind
    ``enospc``). The MRO routes ``__init__`` through RuntimeError,
    which would leave ``errno`` unset — set it explicitly so handlers
    that branch on it see the real thing."""

    def __init__(self, err: int, msg: str):
        super().__init__(err, msg)
        self.errno = err
        self.strerror = msg


# the site table docs cite; registered here so every name exists as a
# code literal in exactly one authoritative place
SITES: Dict[str, str] = {
    "checkpoint.write": "model zip written to disk",
    "checkpoint.read": "model zip opened for restore",
    "data.fetch": "one minibatch produced by an iterator",
    "data.load": "one dataset file read by a fetcher",
    "train.step": "one ElasticTrainer train step",
    "serving.worker.step": "one serving-backend device step",
    "serving.replica": "one request routed to a fleet replica",
    "serving.replica.boot": "one fleet replica boot (scale-up / "
                            "replace successor)",
    "serving.kv.migrate": "one KV lease serialized or rebuilt "
                          "(prefill export, drain migration, import)",
    "parallel.device": "one ParallelWrapper data-parallel mesh step",
    "ps.push.drop": "one compressed-delta push received by the "
                    "parameter server (the worker's packet, lost "
                    "on the wire)",
    "ps.pull.timeout": "one parameter pull served by the parameter "
                       "server (the snapshot reply, lost on the "
                       "wire)",
    "ps.server.restart": "one parameter-server push applied "
                         "(crash-restart the PS from its last "
                         "durable checkpoint)",
    "serving.rollout": "one rollout deployment step (canary boot or "
                       "expansion replace) by the RolloutController",
}

# kinds every site understands via step_fault(), plus the
# site-specific ones its call site interprets — a typo'd or
# site-incompatible kind must fail at plan-parse time, not install
# cleanly and silently inject nothing while counting as fired
_GENERIC_KINDS = frozenset({"crash", "hang", "slow", "error",
                            "enospc"})
SITE_KINDS: Dict[str, frozenset] = {
    "checkpoint.write": _GENERIC_KINDS | {"truncate", "corrupt"},
    "checkpoint.read": _GENERIC_KINDS | {"truncate", "corrupt"},
    "data.fetch": _GENERIC_KINDS,
    "data.load": _GENERIC_KINDS,
    "train.step": _GENERIC_KINDS | {"nan", "sigterm"},
    "serving.worker.step": _GENERIC_KINDS | {"poison"},
    # whole-replica faults are interpreted by the FLEET, not
    # step_fault: kill hard-stops a replica, hang/slow stall all its
    # handlers (the generic kinds would fault the ROUTER's own
    # dispatch thread, which is not what a replica fault means)
    "serving.replica": frozenset({"kill", "hang", "slow"}),
    # boot faults are interpreted by ReplicaFleet._boot_replica:
    # boot_fail raises ReplicaBootError BEFORE the replica starts
    # (the autoscaler's grow() retries with bounded exponential
    # backoff instead of wedging the control loop), boot_slow
    # sleeps args.delay_s first (a replica importing jax forever)
    "serving.replica.boot": frozenset({"boot_fail", "boot_slow"}),
    # KV-migration faults are interpreted by ContinuousBatcher's
    # export/import paths: corrupt flips a payload byte AFTER the
    # CRC is stamped (the importer's integrity check must catch it
    # and the router must fall back), error raises a transient
    # ChaosIOError (the exporting slot stays put and finishes on the
    # incumbent), slow stalls the hop by args.delay_s
    "serving.kv.migrate": frozenset({"corrupt", "slow", "error"}),
    "parallel.device": _GENERIC_KINDS | {"loss"},
    # parameter-server faults are interpreted by ParameterServer's
    # request handlers (parallel/paramserver.py): drop swallows a
    # received push without applying OR acking it (the worker's
    # deadline expires and it retries the SAME sequence number — the
    # dedupe table makes the retry idempotent), timeout swallows a
    # pull reply the same way (the worker re-pulls), restart
    # crash-restarts the server in place from its newest durable
    # checkpoint (workers reconnect, re-hello and re-pull; versions
    # roll back to the last durable generation)
    "ps.push.drop": frozenset({"drop"}),
    "ps.pull.timeout": frozenset({"timeout"}),
    "ps.server.restart": frozenset({"restart"}),
    # rollout faults are interpreted by RolloutController's deploy
    # steps (serving/rollout.py): bad_version wraps the candidate's
    # models so predict returns NaN-poisoned outputs (the comparative
    # gate's error/shadow checks must catch it and roll back),
    # slow_version wraps them to stall args.delay_s per call (the
    # p99 gate must catch it), stall hangs the expansion step itself
    # for args.delay_s while still honoring operator abort
    "serving.rollout": frozenset({"bad_version", "slow_version",
                                  "stall"}),
}


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------

class FaultSpec:
    """One declarative rule: WHERE (site), WHAT (kind), WHEN (``p``
    per-hit probability, or ``at`` — explicit 1-based hit ordinals),
    bounded by ``max_fires``; ``args`` parameterizes the kind
    (``delay_s``, ``keep_frac``, ...)."""

    __slots__ = ("site", "kind", "p", "at", "max_fires", "args")

    def __init__(self, site: str, kind: str, p: float = 0.0,
                 at: Optional[List[int]] = None,
                 max_fires: Optional[int] = None,
                 args: Optional[dict] = None):
        if site not in SITES:
            raise ValueError(
                f"unknown chaos site {site!r}; known sites: "
                f"{sorted(SITES)}")
        if kind not in SITE_KINDS[site]:
            raise ValueError(
                f"chaos site {site!r} does not support kind "
                f"{kind!r}; supported: {sorted(SITE_KINDS[site])}")
        if not (at or p > 0.0):
            raise ValueError(
                f"fault spec for {site!r}/{kind!r} can never fire: "
                "give it p > 0 or an 'at' schedule")
        self.site = site
        self.kind = kind
        self.p = float(p)
        self.at = None if at is None else {int(n) for n in at}
        self.max_fires = max_fires
        self.args = dict(args or {})

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        known = {"site", "kind", "p", "at", "max_fires", "args"}
        extra = set(d) - known
        if extra:
            raise ValueError(
                f"unknown fault-spec key(s) {sorted(extra)}; known: "
                f"{sorted(known)}")
        return cls(d["site"], d["kind"], p=d.get("p", 0.0),
                   at=d.get("at"), max_fires=d.get("max_fires"),
                   args=d.get("args"))

    def to_dict(self) -> dict:
        out = {"site": self.site, "kind": self.kind}
        if self.p:
            out["p"] = self.p
        if self.at is not None:
            out["at"] = sorted(self.at)
        if self.max_fires is not None:
            out["max_fires"] = self.max_fires
        if self.args:
            out["args"] = dict(self.args)
        return out


class FaultPlan:
    def __init__(self, faults: List[FaultSpec],
                 seed: Optional[int] = None):
        self.faults = list(faults)
        self.seed = seed

    def to_dict(self) -> dict:
        out = {"faults": [f.to_dict() for f in self.faults]}
        if self.seed is not None:
            out["seed"] = self.seed
        return out


def parse_plan(plan) -> FaultPlan:
    """Accepts a :class:`FaultPlan`, a list of spec dicts, a dict
    ``{"seed": ..., "faults": [...]}``, a JSON string of either, or a
    path to a JSON file."""
    if isinstance(plan, FaultPlan):
        return plan
    if isinstance(plan, str):
        text = plan.strip()
        if not text.startswith(("{", "[")):
            with open(plan) as f:
                text = f.read()
        plan = json.loads(text)
    if isinstance(plan, list):
        plan = {"faults": plan}
    if not isinstance(plan, dict):
        raise TypeError(f"cannot parse a fault plan from "
                        f"{type(plan).__name__}")
    faults = [s if isinstance(s, FaultSpec) else FaultSpec.from_dict(s)
              for s in plan.get("faults", [])]
    seed = plan.get("seed")
    return FaultPlan(faults, None if seed is None else int(seed))


# ---------------------------------------------------------------------------
# the injector
# ---------------------------------------------------------------------------

class Fault:
    """One fired fault, handed to the call site."""

    __slots__ = ("site", "kind", "args", "ordinal")

    def __init__(self, site: str, kind: str, args: dict, ordinal: int):
        self.site = site
        self.kind = kind
        self.args = args
        self.ordinal = ordinal

    def __repr__(self):
        return (f"Fault(site={self.site!r}, kind={self.kind!r}, "
                f"ordinal={self.ordinal})")


class FaultInjector:
    """Evaluates a :class:`FaultPlan` deterministically.

    Per-site rng streams + per-site hit counters make the fire
    pattern at each site independent of thread interleaving across
    sites; ``seed`` (recorded and logged at install) replays it.
    """

    def __init__(self, plan, seed: Optional[int] = None):
        self.plan = parse_plan(plan)
        if seed is None:
            seed = self.plan.seed
        if seed is None:
            # no seed anywhere: draw one and RECORD it, so any chaotic
            # run is replayable after the fact
            seed = int.from_bytes(os.urandom(4), "big")
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._rngs: Dict[int, random.Random] = {}
        # per-spec fire counts live on the INJECTOR, not the spec: a
        # caller re-installing the same FaultPlan object for a replay
        # must start with fresh max_fires budgets
        self._spec_fired: List[int] = [0] * len(self.plan.faults)
        self.fired_total = 0

    def _rng(self, spec_idx: int, site: str) -> random.Random:
        # one stream per SPEC (stable crc32 of site + spec index), so
        # two p-specs on one site don't perturb each other either
        key = spec_idx
        rng = self._rngs.get(key)
        if rng is None:
            rng = random.Random(
                self.seed ^ zlib.crc32(f"{site}#{spec_idx}".encode()))
            self._rngs[key] = rng
        return rng

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def hit(self, site: str) -> Optional[Fault]:
        """Register one hit at ``site``; returns the fired
        :class:`Fault` (first matching spec wins) or None. Every
        matching p-spec draws its rng exactly once per hit whether or
        not an earlier spec fired, keeping each spec's stream a pure
        function of the site hit count."""
        with self._lock:
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            fired: Optional[Fault] = None
            for i, spec in enumerate(self.plan.faults):
                if spec.site != site:
                    continue
                if spec.at is not None:
                    want = n in spec.at
                else:
                    want = self._rng(i, site).random() < spec.p
                if not want:
                    continue
                if (spec.max_fires is not None
                        and self._spec_fired[i] >= spec.max_fires):
                    continue
                if fired is None:
                    self._spec_fired[i] += 1
                    fired = Fault(site, spec.kind, spec.args, n)
            if fired is not None:
                self.fired_total += 1
        if fired is not None:
            self._account(fired)
        return fired

    def _account(self, fault: Fault) -> None:
        logger.warning("chaos: fault fired at %s (kind=%s, hit #%d)",
                       fault.site, fault.kind, fault.ordinal)
        try:
            from deeplearning4j_tpu.observability.registry import (
                safe_inc)
            safe_inc("chaos_faults_fired_total",
                     help="injected faults fired by the chaos harness",
                     labels={"site": fault.site, "kind": fault.kind})
        except Exception:
            pass
        try:
            from deeplearning4j_tpu.observability import flight_recorder
            rec = flight_recorder.get_recorder()
            if rec is not None:
                rec.record("chaos_fault", site=fault.site,
                           kind=fault.kind, ordinal=fault.ordinal)
        except Exception:
            pass

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)


# ---------------------------------------------------------------------------
# process-wide install + call-site helpers
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None
_INSTALL_LOCK = threading.Lock()


def install(plan, seed: Optional[int] = None) -> FaultInjector:
    """Install a process-wide injector; returns it. The effective
    seed is logged so any run is replayable."""
    global _ACTIVE
    inj = FaultInjector(plan, seed=seed)
    with _INSTALL_LOCK:
        _ACTIVE = inj
    logger.warning(
        "chaos: installed fault plan (%d spec(s), seed=%d — replay "
        "with this seed)", len(inj.plan.faults), inj.seed)
    return inj


def uninstall() -> None:
    global _ACTIVE
    with _INSTALL_LOCK:
        _ACTIVE = None


def current() -> Optional[FaultInjector]:
    return _ACTIVE


def hit(site: str) -> Optional[Fault]:
    """Hot-path entry: ~one attribute read when no injector is
    installed."""
    inj = _ACTIVE
    return None if inj is None else inj.hit(site)


def step_fault(site: str) -> Optional[Fault]:
    """Hit ``site`` and APPLY the generic kinds: ``crash`` raises,
    ``hang``/``slow`` sleep, ``error`` raises a transient
    :class:`ChaosIOError`, ``enospc`` raises :class:`ChaosOSError`.
    Any other kind is returned for the call site to interpret."""
    f = hit(site)
    if f is None:
        return None
    if f.kind == "crash":
        raise SimulatedCrashError(
            f"[chaos] simulated crash at {site} (hit #{f.ordinal})")
    if f.kind in ("hang", "slow"):
        time.sleep(float(f.args.get("delay_s", 0.05)))
        return f
    if f.kind == "error":
        raise ChaosIOError(
            f"[chaos] transient I/O fault at {site} "
            f"(hit #{f.ordinal})")
    if f.kind == "enospc":
        raise ChaosOSError(
            errno.ENOSPC,
            f"[chaos] no space left on device at {site} "
            f"(hit #{f.ordinal})")
    return f


def file_fault(site: str, path: str) -> Optional[Fault]:
    """:func:`step_fault` plus the file kinds, applied to ``path``:
    ``truncate`` keeps only ``args.keep_frac`` (default 0.5) of the
    file; ``corrupt`` overwrites a byte window in the middle."""
    f = step_fault(site)
    if f is None:
        return None
    try:
        size = os.path.getsize(path)
    except OSError:
        return f
    if f.kind == "truncate":
        keep = max(0, int(size * float(f.args.get("keep_frac", 0.5))))
        with open(path, "r+b") as fh:
            fh.truncate(keep)
        logger.warning("chaos: truncated %s to %d/%d bytes", path,
                       keep, size)
    elif f.kind == "corrupt":
        n = min(64, max(1, size // 4))
        pos = max(0, size // 2 - n // 2)
        junk = random.Random((f.ordinal * 2654435761)
                             & 0xFFFFFFFF).randbytes(n)
        with open(path, "r+b") as fh:
            fh.seek(pos)
            fh.write(junk)
        logger.warning("chaos: corrupted %d bytes of %s at offset %d",
                       n, path, pos)
    return f
