"""Platform pinning helper.

A hardware plugin (e.g. the axon TPU tunnel) re-pins jax's platform at
import time, overriding the JAX_PLATFORMS env var — and a dead tunnel
then HANGS the first backend use. Calling this before any backend use
honors an explicit CPU request reliably (the tests/conftest.py idiom,
shared so the CLI and every example stay in sync)."""

from __future__ import annotations

import os

__all__ = ["pin_cpu_platform"]


def pin_cpu_platform() -> None:
    """If JAX_PLATFORMS=cpu is requested, enforce it via jax.config
    (no-op otherwise; safe after backend init)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass    # backends already initialized; use what we have
