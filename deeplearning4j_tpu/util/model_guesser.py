"""ModelGuesser: sniff a file and load it with the right loader.

Mirrors deeplearning4j-core util/ModelGuesser.java (194 LoC): given a
path, detect framework checkpoint zip vs Keras HDF5 vs word-vector
text, and load accordingly.
"""

from __future__ import annotations

import zipfile

__all__ = ["guess_format", "load_model_guess"]


def guess_format(path: str) -> str:
    """'checkpoint' | 'keras_h5' | 'word_vectors' | 'unknown'."""
    with open(path, "rb") as f:
        magic = f.read(8)
    if magic[:4] == b"PK\x03\x04":
        try:
            with zipfile.ZipFile(path) as z:
                names = z.namelist()
            if "configuration.json" in names:
                return "checkpoint"
        except zipfile.BadZipFile:
            pass
        return "unknown"
    if magic[:8] == b"\x89HDF\r\n\x1a\n":
        return "keras_h5"
    try:
        head = magic.decode().split()
        if len(head) >= 1 and head[0].isdigit():
            return "word_vectors"
    except UnicodeDecodeError:
        pass
    return "unknown"


def load_model_guess(path: str):
    kind = guess_format(path)
    if kind == "checkpoint":
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(path)
    if kind == "keras_h5":
        from deeplearning4j_tpu.keras import import_keras_model_and_weights
        return import_keras_model_and_weights(path)
    if kind == "word_vectors":
        from deeplearning4j_tpu.nlp.serializer import read_word_vectors
        return read_word_vectors(path)
    raise ValueError(f"Cannot determine model format of {path}")
