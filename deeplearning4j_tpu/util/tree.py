"""Pytree helpers."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["tree_copy"]


def tree_copy(tree):
    """Deep-copy array leaves. NOT tree_map(identity): the jitted train
    steps donate their param/state buffers, so an aliasing 'copy' would
    be deleted by the next fit() on either network."""
    return jax.tree_util.tree_map(jnp.copy, tree)
