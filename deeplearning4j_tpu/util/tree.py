"""Pytree helpers."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["tree_copy", "tree_flat_vector", "tree_from_flat_vector"]


def tree_copy(tree):
    """Deep-copy array leaves. NOT tree_map(identity): the jitted train
    steps donate their param/state buffers, so an aliasing 'copy' would
    be deleted by the next fit() on either network."""
    return jax.tree_util.tree_map(jnp.copy, tree)


def tree_flat_vector(tree) -> np.ndarray:
    """Concatenate all leaves into one flat host vector (the reference's
    flat params view; shared by both executors' params_flat)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return np.zeros((0,))
    return np.concatenate([np.asarray(l).ravel() for l in leaves])


def tree_from_flat_vector(tree, flat):
    """Inverse of tree_flat_vector: rebuild a tree with the template's
    structure/shapes/dtypes from a flat vector."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out, off = [], 0
    for l in leaves:
        n = int(l.size)
        out.append(jnp.asarray(flat[off:off + n],
                               l.dtype).reshape(l.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)
