"""Model serialization — the checkpoint format.

Mirrors util/ModelSerializer.java:40-127: a ZIP holding
``configuration.json`` (full network config), ``coefficients.npz``
(flat param arrays keyed by pytree path; the analog of the flat
coefficients.bin view), ``updater_state.npz`` (optimizer state),
``state.npz`` (batchnorm running stats etc. — the reference folds these
into params; kept separate here since they are non-trained), and
``metadata.json`` (format version, iteration/epoch counters,
normalizer config). Restore: :func:`restore_model` (reference :137-161).

Backward compat is a contract: ``format_version`` gates migrations and
regression tests pin zips produced by earlier builds (reference
regressiontest/RegressionTest050.java discipline).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["write_model", "restore_model", "restore_normalizer",
           "save_pytree_npz",
           "load_pytree_npz"]

_FORMAT = 1


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def save_pytree_npz(tree) -> bytes:
    buf = io.BytesIO()
    flat = _flatten_with_paths(tree)
    np.savez(buf, **flat)
    return buf.getvalue()


def load_pytree_npz(data: bytes, template) -> Any:
    """Restore arrays into the structure of ``template``."""
    arch = np.load(io.BytesIO(data))
    flat = {k: arch[k] for k in arch.files}
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = "/".join(_path_str(p) for p in path)
        if key not in flat:
            raise KeyError(f"Checkpoint missing array '{key}'")
        arr = flat[key]
        leaves.append(jnp.asarray(arr, getattr(leaf, "dtype", arr.dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def write_model(model, path: str, *, save_updater: bool = True,
                normalizer: Optional[dict] = None) -> None:
    """model: MultiLayerNetwork or ComputationGraph."""
    meta = {
        "format_version": _FORMAT,
        "network_type": type(model).__name__,
        "iteration_count": int(model.iteration_count),
        "epoch_count": int(model.epoch_count),
        "normalizer": normalizer,
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", model.conf.to_json())
        z.writestr("coefficients.npz", save_pytree_npz(model.params))
        z.writestr("state.npz", save_pytree_npz(model.state))
        if save_updater and model.opt_state is not None:
            z.writestr("updater_state.npz",
                       save_pytree_npz(model.opt_state))
        z.writestr("metadata.json", json.dumps(meta))


def restore_model(path: str, *, load_updater: bool = True):
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)
    from deeplearning4j_tpu.nn.conf.multi_layer import (
        MultiLayerConfiguration)

    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
        conf_json = z.read("configuration.json").decode()
        cfg_dict = json.loads(conf_json)
        if cfg_dict.get("network_type") == "ComputationGraph":
            conf = ComputationGraphConfiguration.from_dict(cfg_dict)
            model = ComputationGraph(conf)
        else:
            conf = MultiLayerConfiguration.from_dict(cfg_dict)
            model = MultiLayerNetwork(conf)
        model.init()
        model.params = load_pytree_npz(z.read("coefficients.npz"),
                                       model.params)
        model.state = load_pytree_npz(z.read("state.npz"), model.state)
        if load_updater and "updater_state.npz" in z.namelist():
            try:
                model.opt_state = load_pytree_npz(
                    z.read("updater_state.npz"), model.opt_state)
            except KeyError:
                pass   # optimizer config changed; keep fresh state
        model.iteration_count = meta.get("iteration_count", 0)
        model.epoch_count = meta.get("epoch_count", 0)
    return model


def restore_normalizer(path: str):
    """Rebuild the data normalizer persisted by
    :func:`write_model(..., normalizer=...)` — the reference pairs
    restoreNormalizerFromFile with restoreMultiLayerNetwork
    (util/ModelSerializer.java). Returns None if the checkpoint has no
    normalizer."""
    from deeplearning4j_tpu.data.normalizers import normalizer_from_dict
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
    nd = meta.get("normalizer")
    return normalizer_from_dict(nd) if nd is not None else None
