"""Model serialization — the checkpoint format.

Mirrors util/ModelSerializer.java:40-127: a ZIP holding
``configuration.json`` (full network config), ``coefficients.npz``
(flat param arrays keyed by pytree path; the analog of the flat
coefficients.bin view), ``updater_state.npz`` (optimizer state),
``state.npz`` (batchnorm running stats etc. — the reference folds these
into params; kept separate here since they are non-trained), and
``metadata.json`` (format version, iteration/epoch counters,
normalizer config). Restore: :func:`restore_model` (reference :137-161).

Backward compat is a contract: ``format_version`` gates migrations and
regression tests pin zips produced by earlier builds (reference
regressiontest/RegressionTest050.java discipline).

Durability: every zip written since the chaos PR carries a
``manifest.json`` entry mapping each member to its CRC32, and
:func:`verify_checkpoint` re-checks both the zip's own per-entry CRCs
and the manifest before a restore trusts the file — a truncated or
bit-rotted checkpoint raises :class:`CheckpointIntegrityError`
instead of surfacing as a ``BadZipFile`` (or worse, silently wrong
weights) deep inside the restore. Pre-manifest zips still verify via
the zip CRCs alone, so the v1 regression fixtures keep loading. The
``checkpoint.write`` / ``checkpoint.read`` chaos sites live here, so
every writer and reader in the repo is injectable.
"""

from __future__ import annotations

import io
import json
import zipfile
import zlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import chaos

__all__ = ["write_model", "restore_model", "restore_normalizer",
           "save_pytree_npz", "load_pytree_npz",
           "snapshot_model", "write_snapshot",
           "verify_checkpoint", "CheckpointIntegrityError"]

_FORMAT = 1
_MANIFEST = "manifest.json"


class CheckpointIntegrityError(RuntimeError):
    """The checkpoint file failed CRC/structure verification
    (truncated write, bit rot, interrupted copy). Callers with older
    generations available should quarantine the file and fall back."""


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def save_pytree_npz(tree) -> bytes:
    buf = io.BytesIO()
    flat = _flatten_with_paths(tree)
    np.savez(buf, **flat)
    return buf.getvalue()


def load_pytree_npz(data: bytes, template) -> Any:
    """Restore arrays into the structure of ``template``."""
    arch = np.load(io.BytesIO(data))
    flat = {k: arch[k] for k in arch.files}
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves:
        key = "/".join(_path_str(p) for p in path)
        if key not in flat:
            raise KeyError(f"Checkpoint missing array '{key}'")
        arr = flat[key]
        leaves.append(jnp.asarray(arr, getattr(leaf, "dtype", arr.dtype)))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def snapshot_model(model, *, save_updater: bool = True,
                   normalizer: Optional[dict] = None) -> Dict[str, Any]:
    """Device→host snapshot of everything :func:`write_model`
    persists, decoupled from serialization so a background writer can
    do the expensive part off the train thread.

    The cost on the calling (train) thread is one ``jax.device_get``
    per tree plus a config-JSON render — no npz packing, no DEFLATE,
    no disk. The returned dict is self-contained: later mutation of
    the model (more train steps, an LR drop rebuilding the optimizer)
    cannot leak into a write already in flight."""
    return {
        "conf_json": model.conf.to_json(),
        "params": jax.device_get(model.params),
        "state": jax.device_get(model.state),
        "opt_state": (jax.device_get(model.opt_state)
                      if save_updater and model.opt_state is not None
                      else None),
        "meta": {
            "format_version": _FORMAT,
            "network_type": type(model).__name__,
            "iteration_count": int(model.iteration_count),
            "epoch_count": int(model.epoch_count),
            "normalizer": normalizer,
        },
    }


def write_snapshot(snap: Dict[str, Any], path: str, *,
                   extra_entries: Optional[Dict[str, Any]] = None
                   ) -> None:
    """Serialize a :func:`snapshot_model` dict to a checkpoint zip:
    npz packing + DEFLATE + CRC32 manifest + the ``checkpoint.write``
    chaos site. Runs on whatever thread calls it — this is the half
    ElasticTrainer's async writer takes off the critical path."""
    entries: Dict[str, bytes] = {
        "configuration.json": snap["conf_json"].encode(),
        "coefficients.npz": save_pytree_npz(snap["params"]),
        "state.npz": save_pytree_npz(snap["state"]),
    }
    if snap["opt_state"] is not None:
        entries["updater_state.npz"] = save_pytree_npz(
            snap["opt_state"])
    entries["metadata.json"] = json.dumps(snap["meta"]).encode()
    for name, data in (extra_entries or {}).items():
        entries[name] = data if isinstance(data, bytes) \
            else str(data).encode()
    manifest = {"format_version": _FORMAT,
                "crc32": {n: zlib.crc32(d) & 0xFFFFFFFF
                          for n, d in entries.items()}}
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        for name, data in entries.items():
            z.writestr(name, data)
        z.writestr(_MANIFEST, json.dumps(manifest))
    # chaos site: a preemption/ENOSPC/bit-rot drill against the file
    # just written — restore-side verification must catch whatever
    # this does
    chaos.file_fault("checkpoint.write", path)


def write_model(model, path: str, *, save_updater: bool = True,
                normalizer: Optional[dict] = None,
                extra_entries: Optional[Dict[str, Any]] = None) -> None:
    """model: MultiLayerNetwork or ComputationGraph.

    ``extra_entries`` (name -> str/bytes) ride inside the same zip —
    and inside the integrity manifest — so sidecar payloads like
    ElasticTrainer's data position are covered by the same CRC check
    as the weights (appending after the fact would not be)."""
    write_snapshot(
        snapshot_model(model, save_updater=save_updater,
                       normalizer=normalizer),
        path, extra_entries=extra_entries)


def verify_checkpoint(path: str) -> dict:
    """Integrity-check a checkpoint zip WITHOUT building a model.

    Manifest-bearing zips get every manifested entry re-read and its
    CRC32 recomputed (``ZipFile.read`` verifies the zip's own CRC on
    the way, so one pass covers both checks); pre-manifest zips fall
    back to ``testzip``. Corruption/truncation raises
    :class:`CheckpointIntegrityError`; a transient I/O failure
    (missing file, NFS blip) propagates as the original ``OSError``
    so callers can retry a healthy file instead of quarantining it.
    Returns the manifest (empty dict for pre-manifest zips)."""
    try:
        with zipfile.ZipFile(path, "r") as z:
            names = set(z.namelist())
            for required in ("metadata.json", "configuration.json",
                             "coefficients.npz"):
                if required not in names:
                    raise CheckpointIntegrityError(
                        f"{path}: required entry {required!r} is "
                        "missing (interrupted write?)")
            if _MANIFEST not in names:
                # pre-manifest format: the zip CRCs are all we have
                bad = z.testzip()
                if bad is not None:
                    raise CheckpointIntegrityError(
                        f"{path}: entry {bad!r} fails its zip CRC "
                        "(truncated or corrupted checkpoint)")
                return {}
            manifest = json.loads(z.read(_MANIFEST))
            for name, crc in manifest.get("crc32", {}).items():
                if name not in names:
                    raise CheckpointIntegrityError(
                        f"{path}: entry {name!r} is in the manifest "
                        "but missing from the zip")
                # stream the CRC: a multi-GB coefficients.npz must
                # not be buffered whole just to checksum it (and
                # ZipFile verifies its own entry CRC on this read,
                # so one pass covers both checks)
                actual = 0
                with z.open(name) as fh:
                    while True:
                        chunk = fh.read(1 << 20)
                        if not chunk:
                            break
                        actual = zlib.crc32(chunk, actual)
                actual &= 0xFFFFFFFF
                if actual != int(crc):
                    raise CheckpointIntegrityError(
                        f"{path}: entry {name!r} CRC mismatch "
                        f"(manifest {int(crc):#010x}, actual "
                        f"{actual:#010x})")
            return manifest
    except (zipfile.BadZipFile, zlib.error, EOFError,
            json.JSONDecodeError) as e:
        raise CheckpointIntegrityError(
            f"{path} is not a readable checkpoint zip: {e!r}") from e


def restore_model(path: str, *, load_updater: bool = True):
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)
    from deeplearning4j_tpu.nn.conf.multi_layer import (
        MultiLayerConfiguration)

    # chaos site: at-rest rot / transient read failure discovered at
    # restore time (truncate/corrupt mutate the file before reading)
    chaos.file_fault("checkpoint.read", path)
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
        conf_json = z.read("configuration.json").decode()
        cfg_dict = json.loads(conf_json)
        if cfg_dict.get("network_type") == "ComputationGraph":
            conf = ComputationGraphConfiguration.from_dict(cfg_dict)
            model = ComputationGraph(conf)
        else:
            conf = MultiLayerConfiguration.from_dict(cfg_dict)
            model = MultiLayerNetwork(conf)
        model.init()
        model.params = load_pytree_npz(z.read("coefficients.npz"),
                                       model.params)
        model.state = load_pytree_npz(z.read("state.npz"), model.state)
        if load_updater and "updater_state.npz" in z.namelist():
            try:
                model.opt_state = load_pytree_npz(
                    z.read("updater_state.npz"), model.opt_state)
            except KeyError:
                pass   # optimizer config changed; keep fresh state
        model.iteration_count = meta.get("iteration_count", 0)
        model.epoch_count = meta.get("epoch_count", 0)
    return model


def restore_normalizer(path: str):
    """Rebuild the data normalizer persisted by
    :func:`write_model(..., normalizer=...)` — the reference pairs
    restoreNormalizerFromFile with restoreMultiLayerNetwork
    (util/ModelSerializer.java). Returns None if the checkpoint has no
    normalizer."""
    from deeplearning4j_tpu.data.normalizers import normalizer_from_dict
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
    nd = meta.get("normalizer")
    return normalizer_from_dict(nd) if nd is not None else None
