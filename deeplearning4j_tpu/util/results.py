"""Simple classification-result wrappers.

Mirrors nn/simple/binary/BinaryClassificationResult.java and
nn/simple/multiclass/RankClassificationResult.java: thin convenience
views over raw network outputs.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["BinaryClassificationResult", "RankClassificationResult"]


class BinaryClassificationResult:
    def __init__(self, probabilities, threshold: float = 0.5):
        p = np.asarray(probabilities)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        self.probabilities = p.ravel()
        self.threshold = threshold

    def predicted(self) -> np.ndarray:
        return (self.probabilities >= self.threshold).astype(np.int32)

    def probability_of(self, i: int) -> float:
        return float(self.probabilities[i])


class RankClassificationResult:
    """Per-example class ranking by probability."""

    def __init__(self, probabilities, labels: Optional[List[str]] = None):
        self.probabilities = np.asarray(probabilities)
        n = self.probabilities.shape[-1]
        self.labels = labels or [str(i) for i in range(n)]

    def ranked_classes(self, i: int) -> List[str]:
        order = np.argsort(-self.probabilities[i])
        return [self.labels[j] for j in order]

    def max_outcome(self, i: int) -> str:
        return self.labels[int(np.argmax(self.probabilities[i]))]

    def max_outcomes(self) -> List[str]:
        return [self.max_outcome(i)
                for i in range(self.probabilities.shape[0])]
