from deeplearning4j_tpu.services.nearest_neighbors import (
    NearestNeighborsServer, NearestNeighborsClient,
)

__all__ = ["NearestNeighborsServer", "NearestNeighborsClient"]
