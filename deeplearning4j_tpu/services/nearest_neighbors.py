"""Nearest-neighbors REST service + client.

Mirrors deeplearning4j-nearestneighbor-server
(NearestNeighborsServer.java — Play REST over a serialized VPTree, CLI
via JCommander) and the Java client: a threaded HTTP server exposing
k-NN over a VPTree index. Wire model: JSON (the reference wraps base64
NDArrays; plain float lists here).

Endpoints:
  POST /knn          {"vector": [...], "k": 5} → {"indices", "distances"}
  POST /knnindex     {"index": 12, "k": 5}
  GET  /status       {"points": N, "dims": D}
CLI: python -m deeplearning4j_tpu.services.nearest_neighbors
     --points data.npy --port 9200
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["NearestNeighborsServer", "NearestNeighborsClient"]


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray, port: int = 0,
                 distance: str = "euclidean"):
        self.points = np.asarray(points, np.float64)
        self.tree = VPTree(self.points, distance=distance)
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NearestNeighborsServer":
        tree = self.tree
        points = self.points

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code, obj):
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/status":
                    self._send(200, {"points": int(points.shape[0]),
                                     "dims": int(points.shape[1])})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n).decode())
                except json.JSONDecodeError:
                    self._send(400, {"error": "invalid JSON"})
                    return
                k = int(body.get("k", 5))
                if self.path == "/knn":
                    vec = np.asarray(body["vector"], np.float64)
                    if vec.shape != (points.shape[1],):
                        self._send(400, {"error":
                                         f"vector must have dim "
                                         f"{points.shape[1]}"})
                        return
                elif self.path == "/knnindex":
                    idx = int(body["index"])
                    if not 0 <= idx < points.shape[0]:
                        self._send(400, {"error": "index out of range"})
                        return
                    vec = points[idx]
                else:
                    self._send(404, {"error": "not found"})
                    return
                ids, dists = tree.search(vec, k)
                self._send(200, {"indices": ids,
                                 "distances": [float(d) for d in dists]})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port),
                                          Handler)
        self.port = self._httpd.server_address[1]
        # stored, not anonymous (GL007): stop() joins it
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        logger.info("NearestNeighborsServer on port %d", self.port)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            # release the bound port now, not at GC (GL009)
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)


class NearestNeighborsClient:
    def __init__(self, host: str = "localhost", port: int = 9200):
        self.base = f"http://{host}:{port}"

    def _post(self, path: str, payload: dict) -> dict:
        import urllib.request
        req = urllib.request.Request(
            self.base + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read().decode())

    def knn(self, vector, k: int = 5) -> dict:
        return self._post("/knn", {"vector": list(map(float, vector)),
                                   "k": k})

    def knn_index(self, index: int, k: int = 5) -> dict:
        return self._post("/knnindex", {"index": index, "k": k})


def main():
    p = argparse.ArgumentParser(description="k-NN REST server")
    p.add_argument("--points", required=True,
                   help=".npy file of shape (N, D)")
    p.add_argument("--port", type=int, default=9200)
    p.add_argument("--distance", default="euclidean",
                   choices=["euclidean", "cosine"])
    args = p.parse_args()
    pts = np.load(args.points)
    server = NearestNeighborsServer(pts, args.port, args.distance)
    server.start()
    import time
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
