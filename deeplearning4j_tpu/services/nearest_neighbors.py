"""Nearest-neighbors REST service + client (legacy compat shim).

Mirrors deeplearning4j-nearestneighbor-server
(NearestNeighborsServer.java — Play REST over a serialized VPTree, CLI
via JCommander) and the Java client. Wire model: JSON (the reference
wraps base64 NDArrays; plain float lists here).

.. deprecated::
    This server is the LEGACY surface. The k-NN data path now rides
    the retrieval subsystem's :class:`~..retrieval.index.BruteForceIndex`
    (device matmul top-k instead of the host VPTree walk), and new
    callers should use ``serve --index`` + ``/v1/search`` — batching,
    deadlines, IVF, fleet failover. This module only keeps the old
    ``/knn`` / ``/knnindex`` / ``/status`` wire contract alive on top
    of the same index; the answers agree with the old VPTree ones
    (regression-tested in tests/test_retrieval.py).

Endpoints:
  POST /knn          {"vector": [...], "k": 5} → {"indices", "distances"}
  POST /knnindex     {"index": 12, "k": 5}
  GET  /status       {"points": N, "dims": D}
CLI: python -m deeplearning4j_tpu.services.nearest_neighbors
     --points data.npy --port 9200
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.retrieval.index import BruteForceIndex
from deeplearning4j_tpu.serving.http import (_JsonRequestHandler,
                                             _make_listener)

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["NearestNeighborsServer", "NearestNeighborsClient"]

# legacy clients send one vector per request; anything bigger than
# this is not a k-NN query and must not be buffered
_MAX_BODY = 1 << 20


class NearestNeighborsServer:
    """The legacy wire contract over the new device index.

    Scores come back in the index's higher-is-better convention and
    convert to the distances the old clients expect: euclidean
    ``sqrt(-score)``, cosine ``1 - score`` (exactly the old VPTree
    report, which returned 1-cos).
    """

    def __init__(self, points: np.ndarray, port: int = 0,
                 distance: str = "euclidean"):
        self.points = np.asarray(points, np.float64)
        if self.points.ndim != 2:
            raise ValueError("points must be (N, D); got "
                             f"{self.points.shape}")
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"unsupported distance: {distance!r}")
        self.distance = distance
        self.index = BruteForceIndex(int(self.points.shape[1]),
                                     metric=distance)
        self.index.add(np.arange(self.points.shape[0]),
                       self.points.astype(np.float32))
        self.port = port
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _exact_distances(self, vec: np.ndarray,
                         rows: np.ndarray) -> np.ndarray:
        """float64 distances for the candidate rows: the device
        top-k picks the neighbors, but its float32 score loses the
        low bits near zero — the legacy contract promises a true 0.0
        self-distance, so the reported numbers recompute exactly."""
        pts = self.points[rows]
        if self.distance == "euclidean":
            return np.linalg.norm(pts - vec[None, :], axis=1)
        qn = vec / max(np.linalg.norm(vec), 1e-12)
        norms = np.linalg.norm(pts, axis=1)
        pn = pts / np.maximum(norms, 1e-12)[:, None]
        return 1.0 - pn @ qn

    def _knn(self, vec: np.ndarray, k: int):
        k = max(1, min(int(k), len(self.index)))
        vec = np.asarray(vec, np.float64)
        ids, _ = self.index.search(
            vec.astype(np.float32)[None, :], k=k)
        rows = ids[0][ids[0] >= 0]
        dists = self._exact_distances(vec, rows)
        order = np.argsort(dists, kind="stable")
        return rows[order].tolist(), dists[order].tolist()

    def start(self) -> "NearestNeighborsServer":
        server = self

        class Handler(_JsonRequestHandler):
            def do_GET(self):
                if self.path == "/status":
                    self._send(200,
                               {"points": int(server.points.shape[0]),
                                "dims": int(server.points.shape[1])})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = self._content_length()
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                    return
                if n > _MAX_BODY:
                    self._send(413, {"error": "request body over "
                                              f"{_MAX_BODY} bytes"})
                    return
                try:
                    body = json.loads(
                        self.rfile.read(n).decode() or "{}")
                except (json.JSONDecodeError, UnicodeDecodeError):
                    self._send(400, {"error": "invalid JSON"})
                    return
                try:
                    k = int(body.get("k", 5))
                except (TypeError, ValueError):
                    self._send(400, {"error": "k must be an integer"})
                    return
                if self.path == "/knn":
                    vec = np.asarray(body.get("vector"), np.float64)
                    if vec.shape != (server.points.shape[1],):
                        self._send(400, {"error":
                                         f"vector must have dim "
                                         f"{server.points.shape[1]}"})
                        return
                elif self.path == "/knnindex":
                    try:
                        idx = int(body["index"])
                    except (KeyError, TypeError, ValueError):
                        self._send(400,
                                   {"error": "index must be an int"})
                        return
                    if not 0 <= idx < server.points.shape[0]:
                        self._send(400,
                                   {"error": "index out of range"})
                        return
                    vec = server.points[idx]
                else:
                    self._send(404, {"error": "not found"})
                    return
                ids, dists = server._knn(vec, k)
                self._send(200, {"indices": ids,
                                 "distances": dists})

        self._httpd = _make_listener("127.0.0.1", self.port, Handler)
        self.port = self._httpd.server_address[1]
        # stored, not anonymous (GL007): stop() joins it
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        logger.info("NearestNeighborsServer on port %d (legacy shim "
                    "over BruteForceIndex; prefer serve --index + "
                    "/v1/search)", self.port)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            # release the bound port now, not at GC (GL009)
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)


class NearestNeighborsClient:
    def __init__(self, host: str = "localhost", port: int = 9200):
        self.base = f"http://{host}:{port}"

    def _post(self, path: str, payload: dict) -> dict:
        import urllib.request
        req = urllib.request.Request(
            self.base + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read().decode())

    def knn(self, vector, k: int = 5) -> dict:
        return self._post("/knn", {"vector": list(map(float, vector)),
                                   "k": k})

    def knn_index(self, index: int, k: int = 5) -> dict:
        return self._post("/knnindex", {"index": index, "k": k})


def main():
    p = argparse.ArgumentParser(description="k-NN REST server")
    p.add_argument("--points", required=True,
                   help=".npy file of shape (N, D)")
    p.add_argument("--port", type=int, default=9200)
    p.add_argument("--distance", default="euclidean",
                   choices=["euclidean", "cosine"])
    args = p.parse_args()
    pts = np.load(args.points)
    server = NearestNeighborsServer(pts, args.port, args.distance)
    server.start()
    import time
    while True:
        time.sleep(3600)


if __name__ == "__main__":
    main()
