"""Streaming inference routes.

Mirrors dl4j-streaming (streaming/routes/DL4jServeRouteBuilder.java —
Camel routes wiring Kafka topics to model inference;
streaming/kafka/NDArrayPublisher/NDArrayKafkaClient): a
consume → predict → publish pipeline over pluggable transports. Kafka
itself isn't in this environment, so the broker abstraction has an
in-process implementation (the reference's own tests run an
EmbeddedKafkaCluster for the same reason); a real Kafka transport plugs
into the same Publisher/Consumer SPI.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

__all__ = ["InProcessBroker", "NDArrayPublisher", "NDArrayConsumer",
           "InferenceRoute"]


class InProcessBroker:
    """Topic → subscriber queues (EmbeddedKafkaCluster stand-in)."""

    def __init__(self):
        self._topics: Dict[str, List[queue.Queue]] = {}
        self._lock = threading.Lock()

    def publish(self, topic: str, payload: bytes):
        with self._lock:
            subs = list(self._topics.get(topic, []))
        for q in subs:
            q.put(payload)

    def subscribe(self, topic: str) -> "queue.Queue[bytes]":
        q: "queue.Queue[bytes]" = queue.Queue()
        with self._lock:
            self._topics.setdefault(topic, []).append(q)
        return q


def _encode(arr: np.ndarray) -> bytes:
    return json.dumps({"shape": list(arr.shape),
                       "data": arr.ravel().tolist()}).encode()


def _decode(payload: bytes) -> np.ndarray:
    obj = json.loads(payload.decode())
    return np.asarray(obj["data"], np.float32).reshape(obj["shape"])


class NDArrayPublisher:
    """(streaming/kafka/NDArrayPublisher.java)."""

    def __init__(self, broker: InProcessBroker, topic: str):
        self.broker = broker
        self.topic = topic

    def publish(self, arr: np.ndarray):
        self.broker.publish(self.topic, _encode(np.asarray(arr)))


class NDArrayConsumer:
    """(streaming/kafka/NDArrayConsumer.java)."""

    def __init__(self, broker: InProcessBroker, topic: str):
        self.queue = broker.subscribe(topic)

    def get(self, timeout: Optional[float] = None) -> np.ndarray:
        return _decode(self.queue.get(timeout=timeout))


class InferenceRoute:
    """consume(in_topic) → model.output → publish(out_topic)
    (DL4jServeRouteBuilder semantics). ``start`` spawns the worker;
    errors are published to ``<out_topic>.errors`` instead of killing
    the route."""

    def __init__(self, broker: InProcessBroker, model,
                 in_topic: str, out_topic: str,
                 transform: Optional[Callable] = None):
        self.broker = broker
        self.model = model
        self.in_q = broker.subscribe(in_topic)
        self.out_topic = out_topic
        self.transform = transform
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "InferenceRoute":
        def run():
            while not self._stop.is_set():
                try:
                    payload = self.in_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    x = _decode(payload)
                    if self.transform is not None:
                        x = self.transform(x)
                    y = np.asarray(self.model.output(x))
                    self.broker.publish(self.out_topic, _encode(y))
                except Exception as e:        # route stays alive
                    logger.warning("inference route error: %s", e)
                    self.broker.publish(
                        self.out_topic + ".errors",
                        json.dumps({"error": str(e)}).encode())

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
